"""Text utilities (reference python/mxnet/contrib/text/): vocabulary and
token embeddings backed by dense device tables."""
from __future__ import annotations

import collections
from typing import Dict, List, Optional

import numpy as _np

from ..base import MXNetError
from ..ndarray import NDArray, array, zeros


class Vocabulary:
    """Token vocabulary (reference contrib/text/vocab.py Vocabulary)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        self.unknown_token = unknown_token
        reserved_tokens = list(reserved_tokens or [])
        self._idx_to_token = [unknown_token] + reserved_tokens
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for tok, freq in pairs:
                if freq < min_freq or tok in self._token_to_idx:
                    continue
                self._token_to_idx[tok] = len(self._idx_to_token)
                self._idx_to_token.append(tok)

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def idx_to_token(self) -> List[str]:
        return self._idx_to_token

    @property
    def token_to_idx(self) -> Dict[str, int]:
        return self._token_to_idx

    def to_indices(self, tokens):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        for i in idxs:
            if not 0 <= i < len(self):
                raise MXNetError(f"index {i} out of vocabulary range")
        out = [self._idx_to_token[i] for i in idxs]
        return out[0] if single else out


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """(reference contrib/text/utils.py)"""
    source_str = source_str.lower() if to_lower else source_str
    tokens = [t for seq in source_str.split(seq_delim)
              for t in seq.split(token_delim) if t]
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(tokens)
    return counter


class TokenEmbedding:
    """Pretrained token embedding table (reference
    contrib/text/embedding.py _TokenEmbedding). Loads from a text file of
    `token v1 v2 ...` lines; unknown tokens get init_unknown_vec."""

    def __init__(self, vocabulary: Optional[Vocabulary] = None,
                 vec_len: int = 0):
        self._vocab = vocabulary
        self._vec_len = vec_len
        self._idx_to_vec: Optional[NDArray] = None

    @classmethod
    def from_file(cls, file_path, elem_delim=" ",
                  vocabulary: Optional[Vocabulary] = None,
                  init_unknown_vec=None):
        vecs: Dict[str, _np.ndarray] = {}
        vec_len = 0
        with open(file_path) as f:
            for lineno, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if len(parts) < 2:
                    continue
                if lineno == 0 and len(parts) == 2 and \
                        parts[0].isdigit() and parts[1].isdigit():
                    continue  # fastText-style "<count> <dim>" header
                tok = parts[0]
                try:
                    v = _np.asarray([float(x) for x in parts[1:]], _np.float32)
                except ValueError:
                    continue
                if vec_len == 0:
                    vec_len = len(v)
                elif len(v) != vec_len:
                    continue  # truncated/inconsistent row
                vecs[tok] = v
        if vocabulary is None:
            counter = collections.Counter({t: 1 for t in vecs})
            vocabulary = Vocabulary(counter)
        emb = cls(vocabulary, vec_len)
        table = _np.zeros((len(vocabulary), vec_len), _np.float32)
        if init_unknown_vec is not None:
            table[0] = init_unknown_vec(vec_len)
        for i, tok in enumerate(vocabulary.idx_to_token):
            if tok in vecs:
                table[i] = vecs[tok]
        emb._idx_to_vec = array(table)
        return emb

    @property
    def vec_len(self) -> int:
        return self._vec_len

    @property
    def idx_to_vec(self) -> NDArray:
        return self._idx_to_vec

    @property
    def vocabulary(self) -> Vocabulary:
        return self._vocab

    def get_vecs_by_tokens(self, tokens):
        single = isinstance(tokens, str)
        idxs = self._vocab.to_indices([tokens] if single else tokens)
        out = NDArray(self._idx_to_vec._data[_np.asarray(idxs)])
        return NDArray(out._data[0]) if single else out

    def update_token_vectors(self, tokens, new_vectors):
        idxs = self._vocab.to_indices(
            [tokens] if isinstance(tokens, str) else tokens)
        raw = self._idx_to_vec._data
        nv = new_vectors._data if isinstance(new_vectors, NDArray) \
            else _np.asarray(new_vectors)
        raw = raw.at[_np.asarray(idxs)].set(nv)
        self._idx_to_vec._set_data(raw)


# ---------------------------------------------------------------------------
# Registered embedding catalog
# (reference contrib/text/embedding.py register/create/GloVe/FastText/
#  CustomEmbedding/CompositeEmbedding. Zero-egress stance: the catalogs
#  list the reference's pretrained file names, but files must already sit
#  under embedding_root — there is no downloader; the error says where to
#  put them.)
# ---------------------------------------------------------------------------

_EMBEDDING_REGISTRY: Dict[str, type] = {}


def register(embedding_cls):
    """Class decorator: register a TokenEmbedding subclass under its
    lowercased class name (reference embedding.py:43)."""
    name = embedding_cls.__name__.lower()
    _EMBEDDING_REGISTRY[name] = embedding_cls
    return embedding_cls


def create(embedding_name, **kwargs):
    """create('glove', pretrained_file_name=..., ...) (reference
    embedding.py:66)."""
    name = embedding_name.lower()
    if name not in _EMBEDDING_REGISTRY:
        raise MXNetError(
            f"unknown embedding {embedding_name!r}; registered: "
            f"{sorted(_EMBEDDING_REGISTRY)}")
    return _EMBEDDING_REGISTRY[name](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Catalog of pretrained file names per registered embedding
    (reference embedding.py get_pretrained_file_names)."""
    if embedding_name is not None:
        cls = _EMBEDDING_REGISTRY.get(embedding_name.lower())
        if cls is None:
            raise MXNetError(f"unknown embedding {embedding_name!r}")
        return list(getattr(cls, "pretrained_file_name_sha1", {}))
    # only catalog-backed embeddings appear in the overview (Custom/
    # Composite take explicit paths, not pretrained names)
    return {name: list(cat) for name, cls in _EMBEDDING_REGISTRY.items()
            if (cat := getattr(cls, "pretrained_file_name_sha1", {}))}


class _PretrainedEmbedding(TokenEmbedding):
    """Shared loader for catalog-registered embeddings: resolves
    pretrained_file_name under embedding_root/<name>/ and loads it."""

    pretrained_file_name_sha1: Dict[str, str] = {}

    def __init__(self, pretrained_file_name=None, embedding_root=None,
                 vocabulary=None, init_unknown_vec=None, elem_delim=" "):
        import os
        name = type(self).__name__.lower()
        if pretrained_file_name is None:
            pretrained_file_name = next(iter(self.pretrained_file_name_sha1))
        if pretrained_file_name not in self.pretrained_file_name_sha1:
            raise MXNetError(
                f"{pretrained_file_name!r} is not a known {name} file; "
                f"known: {sorted(self.pretrained_file_name_sha1)}")
        root = os.path.expanduser(
            embedding_root or os.path.join("~", ".mxnet", "embedding"))
        path = os.path.join(root, name, pretrained_file_name)
        if not os.path.exists(path):
            raise MXNetError(
                f"pretrained file {path} not found. This build has no "
                f"downloader (zero egress); place the {name} file there "
                "yourself, or use CustomEmbedding for arbitrary paths")
        loaded = TokenEmbedding.from_file(
            path, elem_delim=elem_delim, vocabulary=vocabulary,
            init_unknown_vec=init_unknown_vec)
        super().__init__(loaded.vocabulary, loaded.vec_len)
        self._idx_to_vec = loaded.idx_to_vec


@register
class GloVe(_PretrainedEmbedding):
    """GloVe catalog (reference embedding.py:484; file list mirrors the
    reference's pretrained_file_name_sha1 keys)."""

    pretrained_file_name_sha1 = {
        "glove.42B.300d.txt": "", "glove.6B.50d.txt": "",
        "glove.6B.100d.txt": "", "glove.6B.200d.txt": "",
        "glove.6B.300d.txt": "", "glove.840B.300d.txt": "",
        "glove.twitter.27B.25d.txt": "", "glove.twitter.27B.50d.txt": "",
        "glove.twitter.27B.100d.txt": "", "glove.twitter.27B.200d.txt": "",
    }


@register
class FastText(_PretrainedEmbedding):
    """fastText catalog (reference embedding.py:556)."""

    pretrained_file_name_sha1 = {
        "wiki.en.vec": "", "wiki.simple.vec": "", "wiki.zh.vec": "",
        "wiki.de.vec": "", "wiki.fr.vec": "", "wiki.es.vec": "",
        "crawl-300d-2M.vec": "",
    }


@register
class CustomEmbedding(TokenEmbedding):
    """User-provided `token v1 v2 ...` file at an arbitrary path
    (reference embedding.py:638)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 vocabulary=None, init_unknown_vec=None):
        loaded = TokenEmbedding.from_file(
            pretrained_file_path, elem_delim=elem_delim,
            vocabulary=vocabulary, init_unknown_vec=init_unknown_vec)
        super().__init__(loaded.vocabulary, loaded.vec_len)
        self._idx_to_vec = loaded.idx_to_vec


@register
class CompositeEmbedding(TokenEmbedding):
    """Concatenation of several TokenEmbeddings over one vocabulary
    (reference embedding.py:680): vec_len = sum of the parts; lookups
    concatenate each part's vector for the token."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        if not token_embeddings:
            raise MXNetError("CompositeEmbedding needs >= 1 embeddings")
        vec_len = sum(e.vec_len for e in token_embeddings)
        super().__init__(vocabulary, vec_len)
        parts = []
        for emb in token_embeddings:
            # remap each part's table onto the composite vocabulary; tokens
            # the part has never seen fall back to its unknown (index 0) row
            src = _np.asarray(emb.idx_to_vec._data)
            idxs = _np.asarray(
                [emb.vocabulary.token_to_idx.get(t, 0)
                 for t in vocabulary.idx_to_token])
            parts.append(src[idxs])
        self._idx_to_vec = array(_np.concatenate(parts, axis=1))
