"""Text utilities (reference python/mxnet/contrib/text/): vocabulary and
token embeddings backed by dense device tables."""
from __future__ import annotations

import collections
from typing import Dict, List, Optional

import numpy as _np

from ..base import MXNetError
from ..ndarray import NDArray, array, zeros


class Vocabulary:
    """Token vocabulary (reference contrib/text/vocab.py Vocabulary)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        self.unknown_token = unknown_token
        reserved_tokens = list(reserved_tokens or [])
        self._idx_to_token = [unknown_token] + reserved_tokens
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for tok, freq in pairs:
                if freq < min_freq or tok in self._token_to_idx:
                    continue
                self._token_to_idx[tok] = len(self._idx_to_token)
                self._idx_to_token.append(tok)

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def idx_to_token(self) -> List[str]:
        return self._idx_to_token

    @property
    def token_to_idx(self) -> Dict[str, int]:
        return self._token_to_idx

    def to_indices(self, tokens):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        for i in idxs:
            if not 0 <= i < len(self):
                raise MXNetError(f"index {i} out of vocabulary range")
        out = [self._idx_to_token[i] for i in idxs]
        return out[0] if single else out


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """(reference contrib/text/utils.py)"""
    source_str = source_str.lower() if to_lower else source_str
    tokens = [t for seq in source_str.split(seq_delim)
              for t in seq.split(token_delim) if t]
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(tokens)
    return counter


class TokenEmbedding:
    """Pretrained token embedding table (reference
    contrib/text/embedding.py _TokenEmbedding). Loads from a text file of
    `token v1 v2 ...` lines; unknown tokens get init_unknown_vec."""

    def __init__(self, vocabulary: Optional[Vocabulary] = None,
                 vec_len: int = 0):
        self._vocab = vocabulary
        self._vec_len = vec_len
        self._idx_to_vec: Optional[NDArray] = None

    @classmethod
    def from_file(cls, file_path, elem_delim=" ",
                  vocabulary: Optional[Vocabulary] = None,
                  init_unknown_vec=None):
        vecs: Dict[str, _np.ndarray] = {}
        vec_len = 0
        with open(file_path) as f:
            for lineno, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if len(parts) < 2:
                    continue
                if lineno == 0 and len(parts) == 2 and \
                        parts[0].isdigit() and parts[1].isdigit():
                    continue  # fastText-style "<count> <dim>" header
                tok = parts[0]
                try:
                    v = _np.asarray([float(x) for x in parts[1:]], _np.float32)
                except ValueError:
                    continue
                if vec_len == 0:
                    vec_len = len(v)
                elif len(v) != vec_len:
                    continue  # truncated/inconsistent row
                vecs[tok] = v
        if vocabulary is None:
            counter = collections.Counter({t: 1 for t in vecs})
            vocabulary = Vocabulary(counter)
        emb = cls(vocabulary, vec_len)
        table = _np.zeros((len(vocabulary), vec_len), _np.float32)
        if init_unknown_vec is not None:
            table[0] = init_unknown_vec(vec_len)
        for i, tok in enumerate(vocabulary.idx_to_token):
            if tok in vecs:
                table[i] = vecs[tok]
        emb._idx_to_vec = array(table)
        return emb

    @property
    def vec_len(self) -> int:
        return self._vec_len

    @property
    def idx_to_vec(self) -> NDArray:
        return self._idx_to_vec

    @property
    def vocabulary(self) -> Vocabulary:
        return self._vocab

    def get_vecs_by_tokens(self, tokens):
        single = isinstance(tokens, str)
        idxs = self._vocab.to_indices([tokens] if single else tokens)
        out = NDArray(self._idx_to_vec._data[_np.asarray(idxs)])
        return NDArray(out._data[0]) if single else out

    def update_token_vectors(self, tokens, new_vectors):
        idxs = self._vocab.to_indices(
            [tokens] if isinstance(tokens, str) else tokens)
        raw = self._idx_to_vec._data
        nv = new_vectors._data if isinstance(new_vectors, NDArray) \
            else _np.asarray(new_vectors)
        raw = raw.at[_np.asarray(idxs)].set(nv)
        self._idx_to_vec._set_data(raw)
