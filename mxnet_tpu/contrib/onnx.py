"""ONNX interop (reference python/mxnet/contrib/onnx/ — mx2onnx export +
onnx2mx import, 4,209 lines across the two translator sets).

Self-contained: when the `onnx` pip package is installed it is used
directly; otherwise serialization falls back to the vendored protobuf
subset in `onnx_proto/` (same wire format — files interchange with stock
onnx/onnxruntime). Both `export_model` and `import_model` therefore always
work, unlike the reference which hard-requires the pip package.

Coverage: 136 MXNet op names on the export side and 116 ONNX op types on
the import side (see `export_op_names()` / `import_op_names()`) — a
superset of the reference's 100 registered export / 93 import names —
enough for the vision model zoo (resnet/vgg/alexnet/mobilenet/squeezenet/
densenet) to roundtrip with numerical equality — tests/test_onnx_zoo.py.
Target opset: 11-13 semantics (Slice/Clip/Pad bounds as inputs, Reshape
shape as input; Squeeze/Unsqueeze/ReduceSum accept either attr or input
axes on import).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as _np

from ..base import MXNetError
from . import onnx_proto as _shim

try:
    import onnx as _onnx
    from onnx import helper as _oh, TensorProto as _TP
    from onnx import numpy_helper as _onh
except ImportError:
    # the vendored subset serves the same API surface
    _onnx, _oh, _TP, _onh = _shim, _shim.helper, _shim.TensorProto, \
        _shim.numpy_helper


_NP2TP = {"float32": _TP.FLOAT, "float64": _TP.DOUBLE, "float16": _TP.FLOAT16,
          "int32": _TP.INT32, "int64": _TP.INT64, "int8": _TP.INT8,
          "uint8": _TP.UINT8, "bool": _TP.BOOL}
_TP2NP = {v: k for k, v in _NP2TP.items()}


def _tp_of(np_dtype) -> int:
    return _NP2TP.get(_np.dtype(np_dtype).name, _TP.FLOAT)


# ===========================================================================
# Export (mx2onnx)
# ===========================================================================

class _Exporter:
    """Per-export state: node list, initializer list, fresh-name counter.
    Handlers emit one or more ONNX nodes and may register constant
    initializer inputs (opset-11 style Reshape/Slice/Clip bounds)."""

    def __init__(self, dtype_elem):
        self.nodes: List = []
        self.initializers: List = []
        self.elem = dtype_elem
        # tensor name -> TensorProto dtype, for outputs that are NOT the
        # graph element type (int argmax indices, Shape results) so the
        # graph's value_infos declare the true type
        self.value_dtypes: Dict[str, int] = {}
        self._n = 0

    def fresh(self, hint: str) -> str:
        self._n += 1
        return f"_{hint}_{self._n}"

    def const(self, hint: str, arr: _np.ndarray) -> str:
        name = self.fresh(hint)
        arr = _np.asarray(arr)
        self.initializers.append(_oh.make_tensor(
            name, _tp_of(arr.dtype), arr.shape, arr.flatten().tolist()))
        return name

    def emit(self, op: str, ins: List[str], outs: List[str], **attrs):
        self.nodes.append(_oh.make_node(
            op, ins, outs, name=self.fresh(op.lower()), **attrs))
        return outs[0]


# -- 1:1 tables --------------------------------------------------------------

_UNARY_EXPORT = {
    "relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
    "softsign": "Softsign", "softrelu": "Softplus", "exp": "Exp",
    "log": "Log", "sqrt": "Sqrt", "abs": "Abs", "negative": "Neg",
    "floor": "Floor", "ceil": "Ceil", "round": "Round", "sign": "Sign",
    "sin": "Sin", "cos": "Cos", "tan": "Tan", "arcsin": "Asin",
    "arccos": "Acos", "arctan": "Atan", "sinh": "Sinh", "cosh": "Cosh",
    "arcsinh": "Asinh", "arccosh": "Acosh", "arctanh": "Atanh",
    "erf": "Erf", "reciprocal": "Reciprocal", "identity": "Identity",
    "_copy": "Identity", "Flatten": "Flatten",
}

_BINARY_EXPORT = {
    "elemwise_add": "Add", "broadcast_add": "Add",
    "elemwise_sub": "Sub", "broadcast_sub": "Sub",
    "elemwise_mul": "Mul", "broadcast_mul": "Mul",
    "elemwise_div": "Div", "broadcast_div": "Div",
    "broadcast_power": "Pow",
    "broadcast_maximum": "Max", "broadcast_minimum": "Min",
    "dot": "MatMul",
}

# mxnet scalar-op name -> (onnx op, scalar-side): "r" = scalar is lhs
_SCALAR_EXPORT = {
    "_plus_scalar": ("Add", "l"), "_minus_scalar": ("Sub", "l"),
    "_rminus_scalar": ("Sub", "r"), "_mul_scalar": ("Mul", "l"),
    "_div_scalar": ("Div", "l"), "_rdiv_scalar": ("Div", "r"),
    "_power_scalar": ("Pow", "l"), "_rpower_scalar": ("Pow", "r"),
    "_maximum_scalar": ("Max", "l"), "_minimum_scalar": ("Min", "l"),
}

# comparisons: ONNX result is bool; MXNet contract is float32 0/1
_COMPARE_EXPORT = {
    "broadcast_equal": "Equal", "broadcast_greater": "Greater",
    "broadcast_lesser": "Less", "broadcast_greater_equal": "GreaterOrEqual",
    "broadcast_lesser_equal": "LessOrEqual",
}

_LOGICAL_EXPORT = {"broadcast_logical_and": "And",
                   "broadcast_logical_or": "Or",
                   "broadcast_logical_xor": "Xor"}

_REDUCE_EXPORT = {"sum": "ReduceSum", "mean": "ReduceMean",
                  "max": "ReduceMax", "min": "ReduceMin",
                  "prod": "ReduceProd"}


def _axes_list(axis):
    if axis is None:
        return None
    if isinstance(axis, (tuple, list)):
        return [int(a) for a in axis]
    return [int(axis)]


def _export_node(ex: _Exporter, op_name: str, p: Dict, ins: List[str],
                 out: str):
    """Translate one mxnet graph node into ONNX node(s). Raises MXNetError
    for unsupported ops (reference mx2onnx raises AttributeError alike)."""
    if op_name in _UNARY_EXPORT:
        return ex.emit(_UNARY_EXPORT[op_name], ins, [out])
    if op_name in _BINARY_EXPORT:
        return ex.emit(_BINARY_EXPORT[op_name], ins, [out])
    if op_name == "add_n":
        return ex.emit("Sum", ins, [out])
    if op_name in ("BlockGrad", "MakeLoss", "make_loss", "stop_gradient"):
        # gradient-flow markers: inference-graph identity
        return ex.emit("Identity", [ins[0]], [out])
    if op_name == "square":
        return ex.emit("Mul", [ins[0], ins[0]], [out])
    if op_name == "size_array":
        ex.value_dtypes[out] = _TP.INT64
        return ex.emit("Size", ins, [out])
    if op_name in ("_maximum", "_minimum"):
        return ex.emit("Max" if op_name == "_maximum" else "Min", ins, [out])
    if op_name == "_power":
        return ex.emit("Pow", ins, [out])
    if op_name == "SoftmaxOutput":
        # label input + loss gradient are train-time machinery; the
        # inference contract is softmax over axis 1 (multi_output) or -1
        return ex.emit("Softmax", [ins[0]], [out],
                       axis=1 if p.get("multi_output") else -1)
    if op_name == "LogisticRegressionOutput":
        return ex.emit("Sigmoid", [ins[0]], [out])
    if op_name == "LRN":
        # identical parameterizations: x / (bias + alpha/size * sqsum)^beta
        return ex.emit("LRN", ins, [out], alpha=float(p.get("alpha", 1e-4)),
                       beta=float(p.get("beta", 0.75)),
                       bias=float(p.get("knorm", 2.0)), size=int(p["nsize"]))
    if op_name == "Crop":
        if len(ins) > 1 or p.get("center_crop"):
            raise MXNetError("ONNX export: Crop supports the static "
                             "offset+h_w form only")
        oy, ox = (int(v) for v in p.get("offset", (0, 0)))
        th, tw = (int(v) for v in p.get("h_w", (0, 0)))
        return ex.emit(
            "Slice",
            [ins[0],
             ex.const("starts", _np.asarray([oy, ox], _np.int64)),
             ex.const("ends", _np.asarray([oy + th, ox + tw], _np.int64)),
             ex.const("axes", _np.asarray([2, 3], _np.int64))], [out])
    if op_name == "ROIPooling":
        ph, pw = (int(v) for v in p["pooled_size"])
        return ex.emit("MaxRoiPool", ins, [out], pooled_shape=[ph, pw],
                       spatial_scale=float(p.get("spatial_scale", 1.0)))
    if op_name in ("_linalg_gemm2", "linalg_gemm2"):
        alpha = float(p.get("alpha", 1.0))
        if p.get("transpose_a") or p.get("transpose_b"):
            # rank-2 contract: Gemm carries both transposes and alpha
            return ex.emit("Gemm", ins, [out], alpha=alpha,
                           transA=int(bool(p.get("transpose_a"))),
                           transB=int(bool(p.get("transpose_b"))))
        if alpha == 1.0:
            return ex.emit("MatMul", ins, [out])
        m = ex.emit("MatMul", ins, [ex.fresh("mm")])
        c = ex.const("alpha", _np.float32(alpha))
        return ex.emit("Mul", [m, c], [out])
    if op_name in ("_random_uniform", "_random_normal"):
        # the key input is the executor's RNG var — ONNX generators carry
        # their own implementation-defined RNG, so it is dropped
        shape = p.get("shape", (1,))
        shape = [int(shape)] if isinstance(shape, int) else \
            [int(s) for s in shape]
        if op_name == "_random_uniform":
            return ex.emit("RandomUniform", [], [out], shape=shape,
                           low=float(p.get("low", 0.0)),
                           high=float(p.get("high", 1.0)))
        return ex.emit("RandomNormal", [], [out], shape=shape,
                       mean=float(p.get("loc", 0.0)),
                       scale=float(p.get("scale", 1.0)))
    if op_name in ("_random_uniform_like", "_random_normal_like"):
        if op_name == "_random_uniform_like":
            return ex.emit("RandomUniformLike", [ins[0]], [out],
                           low=float(p.get("low", 0.0)),
                           high=float(p.get("high", 1.0)))
        return ex.emit("RandomNormalLike", [ins[0]], [out],
                       mean=float(p.get("loc", 0.0)),
                       scale=float(p.get("scale", 1.0)))
    if op_name == "_sample_multinomial":
        # mxnet samples from probability rows; ONNX Multinomial takes
        # unnormalized log-probs — Log bridges exactly. Multinomial requires
        # rank-2 input and emits (batch, sample_size); a tuple draw shape
        # gets its rank back with a trailing Reshape (0 = copy batch dim)
        shape = p.get("shape")
        if shape is None:
            n, multi = 1, None
        elif isinstance(shape, (int, float)):
            n, multi = int(shape), None
        else:
            dims = [int(s) for s in shape]
            n, multi = int(_np.prod(dims)), (dims if len(dims) > 1 else None)
        lg = ex.emit("Log", [ins[0]], [ex.fresh("logp")])
        ex.value_dtypes[out] = _TP.INT32
        if multi is None:
            return ex.emit("Multinomial", [lg], [out], sample_size=n,
                           dtype=_TP.INT32)
        m = ex.emit("Multinomial", [lg], [ex.fresh("mn")], sample_size=n,
                    dtype=_TP.INT32)
        c = ex.const("shape", _np.asarray([0] + multi, _np.int64))
        return ex.emit("Reshape", [m, c], [out])

    if op_name in _SCALAR_EXPORT:
        onnx_op, side = _SCALAR_EXPORT[op_name]
        c = ex.const("scalar", _np.float32(p.get("scalar", 0.0)))
        pair = [c, ins[0]] if side == "r" else [ins[0], c]
        return ex.emit(onnx_op, pair, [out])

    if op_name in _COMPARE_EXPORT:
        b = ex.emit(_COMPARE_EXPORT[op_name], ins, [ex.fresh("cmp")])
        return ex.emit("Cast", [b], [out], to=_TP.FLOAT)
    if op_name == "broadcast_not_equal":
        e = ex.emit("Equal", ins, [ex.fresh("eq")])
        n = ex.emit("Not", [e], [ex.fresh("ne")])
        return ex.emit("Cast", [n], [out], to=_TP.FLOAT)
    if op_name in _LOGICAL_EXPORT:
        bs = [ex.emit("Cast", [i], [ex.fresh("b")], to=_TP.BOOL) for i in ins]
        r = ex.emit(_LOGICAL_EXPORT[op_name], bs, [ex.fresh("lg")])
        return ex.emit("Cast", [r], [out], to=_TP.FLOAT)
    if op_name == "logical_not":
        b = ex.emit("Cast", ins, [ex.fresh("b")], to=_TP.BOOL)
        n = ex.emit("Not", [b], [ex.fresh("nt")])
        return ex.emit("Cast", [n], [out], to=_TP.FLOAT)

    if op_name in _REDUCE_EXPORT:
        onnx_op = _REDUCE_EXPORT[op_name]
        attrs = {"keepdims": int(bool(p.get("keepdims", False)))}
        axes = _axes_list(p.get("axis"))
        if onnx_op == "ReduceSum":
            # opset 13 moved ReduceSum's axes to an input (the other
            # Reduce* ops keep the attribute until opset 18)
            rs_ins = [ins[0]]
            if axes is not None:
                rs_ins.append(ex.const(
                    "axes", _np.asarray(axes, _np.int64)))
            return ex.emit("ReduceSum", rs_ins, [out], **attrs)
        if axes is not None:
            attrs["axes"] = axes
        return ex.emit(onnx_op, ins, [out], **attrs)
    if op_name == "norm":
        if int(p.get("ord", 2)) != 2:
            raise MXNetError("ONNX export: norm supports ord=2 only")
        attrs = {"keepdims": int(bool(p.get("keepdims", False)))}
        axes = _axes_list(p.get("axis"))
        if axes is not None:
            attrs["axes"] = axes
        return ex.emit("ReduceL2", ins, [out], **attrs)
    if op_name in ("argmax", "argmin"):
        if p.get("axis") is None:
            raise MXNetError(f"ONNX export: {op_name} needs an explicit axis")
        a = ex.emit("ArgMax" if op_name == "argmax" else "ArgMin", ins,
                    [ex.fresh("arg")], axis=int(p["axis"]),
                    keepdims=int(bool(p.get("keepdims", False))))
        # honor the op's dtype: float32 is the MXNet default contract,
        # int32/int64 is the exact-indices mode — casting that to float
        # would reintroduce the 2^24 rounding the override exists to avoid
        dt = str(p.get("dtype", "float32"))
        tp = _NP2TP.get(dt, _TP.FLOAT)
        if tp != ex.elem:
            ex.value_dtypes[out] = tp
        return ex.emit("Cast", [a], [out], to=tp)

    # -- shape / movement ---------------------------------------------------
    if op_name == "Reshape":
        shape = p.get("shape")
        if shape is None:
            raise MXNetError("ONNX export: Reshape without static shape")
        c = ex.const("shape", _np.asarray(shape, _np.int64))
        return ex.emit("Reshape", [ins[0], c], [out])
    if op_name == "transpose":
        axes = p.get("axes")
        attrs = {"perm": [int(a) for a in axes]} if axes else {}
        return ex.emit("Transpose", ins, [out], **attrs)
    if op_name == "expand_dims":
        # opset 13+: Unsqueeze axes is an input, not an attribute
        ax = ex.const("axes", _np.asarray([int(p["axis"])], _np.int64))
        return ex.emit("Unsqueeze", [ins[0], ax], [out])
    if op_name == "squeeze":
        sq_ins = [ins[0]]
        if p.get("axis") is not None:
            sq_ins.append(ex.const(
                "axes", _np.asarray(_axes_list(p["axis"]), _np.int64)))
        return ex.emit("Squeeze", sq_ins, [out])
    if op_name == "Concat":
        return ex.emit("Concat", ins, [out], axis=int(p.get("dim", 1)))
    if op_name == "stack":
        axis = int(p.get("axis", 0))
        ax = ex.const("axes", _np.asarray([axis], _np.int64))
        us = [ex.emit("Unsqueeze", [i, ax], [ex.fresh("us")]) for i in ins]
        return ex.emit("Concat", us, [out], axis=axis)
    if op_name == "slice":
        begin = list(p.get("begin", ()))
        end = list(p.get("end", ()))
        step = list(p.get("step") or ())
        n = len(begin)
        starts = [int(b) if b is not None else 0 for b in begin]
        ends = [int(e) if e is not None else (1 << 62) for e in end]
        steps = [int(step[i]) if i < len(step) and step[i] else 1
                 for i in range(n)]
        return ex.emit(
            "Slice",
            [ins[0], ex.const("starts", _np.asarray(starts, _np.int64)),
             ex.const("ends", _np.asarray(ends, _np.int64)),
             ex.const("axes", _np.arange(n, dtype=_np.int64)),
             ex.const("steps", _np.asarray(steps, _np.int64))], [out])
    if op_name == "slice_axis":
        end = p.get("end")
        return ex.emit(
            "Slice",
            [ins[0],
             ex.const("starts", _np.asarray([int(p["begin"])], _np.int64)),
             ex.const("ends", _np.asarray(
                 [int(end) if end is not None else (1 << 62)], _np.int64)),
             ex.const("axes", _np.asarray([int(p["axis"])], _np.int64))],
            [out])
    if op_name in ("SliceChannel", "split"):
        num = int(p.get("num_outputs", 2))
        outs = [out if i == 0 else f"{out}__{i}" for i in range(num)]
        ex.emit("Split", ins, outs, axis=int(p.get("axis", 1)))
        if p.get("squeeze_axis"):
            raise MXNetError("ONNX export: SliceChannel squeeze_axis "
                             "unsupported")
        return outs
    if op_name == "tile":
        reps = p.get("reps")
        c = ex.const("reps", _np.asarray(reps, _np.int64))
        return ex.emit("Tile", [ins[0], c], [out])
    if op_name == "pad":
        pw = list(p.get("pad_width", ()))
        n = len(pw) // 2
        begins = [int(pw[2 * i]) for i in range(n)]
        ends = [int(pw[2 * i + 1]) for i in range(n)]
        mode = {"constant": "constant", "edge": "edge",
                "reflect": "reflect"}[p.get("mode", "constant")]
        c = ex.const("pads", _np.asarray(begins + ends, _np.int64))
        v = ex.const("padv", _np.float32(p.get("constant_value", 0.0)))
        return ex.emit("Pad", [ins[0], c, v], [out], mode=mode)
    if op_name == "clip":
        lo = ex.const("clip_min", _np.float32(p.get("a_min", -3.4e38)))
        hi = ex.const("clip_max", _np.float32(p.get("a_max", 3.4e38)))
        return ex.emit("Clip", [ins[0], lo, hi], [out])
    if op_name == "Cast":
        to = _NP2TP.get(str(p.get("dtype", "float32")), _TP.FLOAT)
        return ex.emit("Cast", ins, [out], to=to)
    if op_name == "where":
        b = ex.emit("Cast", [ins[0]], [ex.fresh("cond")], to=_TP.BOOL)
        return ex.emit("Where", [b, ins[1], ins[2]], [out])
    if op_name == "broadcast_to":
        shape = [int(s) if s != 0 else 1 for s in p.get("shape", ())]
        c = ex.const("shape", _np.asarray(shape, _np.int64))
        return ex.emit("Expand", [ins[0], c], [out])
    if op_name == "depth_to_space":
        return ex.emit("DepthToSpace", ins, [out],
                       blocksize=int(p["block_size"]))
    if op_name == "space_to_depth":
        return ex.emit("SpaceToDepth", ins, [out],
                       blocksize=int(p["block_size"]))
    if op_name in ("zeros_like", "ones_like"):
        # ConstantOfShape(Shape(x)): type-correct for any input dtype and
        # immune to inf/nan in x (a Mul-by-0 encoding is neither)
        shp = ex.emit("Shape", ins, [ex.fresh("shape")])
        fill = 0.0 if op_name == "zeros_like" else 1.0
        val = _oh.make_tensor(ex.fresh("fill"), ex.elem, [1], [fill])
        return ex.emit("ConstantOfShape", [shp], [out], value=val)

    # -- NN -----------------------------------------------------------------
    if op_name == "Activation":
        act = p.get("act_type", "relu")
        m = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus", "softsign": "Softsign"}
        if act not in m:
            raise MXNetError(f"ONNX export: Activation {act}")
        return ex.emit(m[act], ins, [out])
    if op_name == "LeakyReLU":
        act = p.get("act_type", "leaky")
        if act == "leaky":
            return ex.emit("LeakyRelu", ins, [out],
                           alpha=float(p.get("slope", 0.25)))
        if act == "elu":
            return ex.emit("Elu", ins, [out],
                           alpha=float(p.get("slope", 0.25)))
        if act == "selu":
            return ex.emit("Selu", ins, [out])
        if act == "gelu":
            # exact gelu via Erf: 0.5 x (1 + erf(x / sqrt(2)))
            c = ex.const("sqrt2", _np.float32(_np.sqrt(2.0)))
            d = ex.emit("Div", [ins[0], c], [ex.fresh("g")])
            e = ex.emit("Erf", [d], [ex.fresh("g")])
            one = ex.const("one", _np.float32(1.0))
            a = ex.emit("Add", [e, one], [ex.fresh("g")])
            m_ = ex.emit("Mul", [ins[0], a], [ex.fresh("g")])
            half = ex.const("half", _np.float32(0.5))
            return ex.emit("Mul", [m_, half], [out])
        raise MXNetError(f"ONNX export: LeakyReLU {act}")
    if op_name == "gelu":
        return _export_node(ex, "LeakyReLU", {"act_type": "gelu"}, ins, out)
    if op_name == "silu":
        s = ex.emit("Sigmoid", ins, [ex.fresh("sg")])
        return ex.emit("Mul", [ins[0], s], [out])
    if op_name == "hard_sigmoid":
        return ex.emit("HardSigmoid", ins, [out],
                       alpha=float(p.get("alpha", 0.2)),
                       beta=float(p.get("beta", 0.5)))
    if op_name == "softmax":
        return ex.emit("Softmax", ins, [out], axis=int(p.get("axis", -1)))
    if op_name == "log_softmax":
        return ex.emit("LogSoftmax", ins, [out], axis=int(p.get("axis", -1)))
    if op_name == "FullyConnected":
        return ex.emit("Gemm", ins, [out], transB=1)
    if op_name == "Convolution":
        k = tuple(p.get("kernel", ()))
        attrs = {"kernel_shape": list(k)}
        if p.get("stride"):
            attrs["strides"] = [int(s) for s in p["stride"]]
        if p.get("pad"):
            attrs["pads"] = [int(v) for v in p["pad"]] * 2
        if p.get("dilate"):
            attrs["dilations"] = [int(v) for v in p["dilate"]]
        if p.get("num_group", 1) != 1:
            attrs["group"] = int(p["num_group"])
        return ex.emit("Conv", ins, [out], **attrs)
    if op_name == "Deconvolution":
        k = tuple(p.get("kernel", ()))
        attrs = {"kernel_shape": list(k)}
        if p.get("stride"):
            attrs["strides"] = [int(s) for s in p["stride"]]
        if p.get("pad"):
            attrs["pads"] = [int(v) for v in p["pad"]] * 2
        if p.get("dilate"):
            attrs["dilations"] = [int(v) for v in p["dilate"]]
        if p.get("num_group", 1) != 1:
            attrs["group"] = int(p["num_group"])
        if p.get("adj"):
            attrs["output_padding"] = [int(v) for v in p["adj"]]
        return ex.emit("ConvTranspose", ins, [out], **attrs)
    if op_name == "Pooling":
        pool = p.get("pool_type", "max")
        if p.get("global_pool"):
            return ex.emit(
                "GlobalMaxPool" if pool == "max" else "GlobalAveragePool",
                ins, [out])
        attrs = {"kernel_shape": list(p.get("kernel", (1, 1)))}
        if p.get("stride"):
            attrs["strides"] = [int(s) for s in p["stride"]]
        if p.get("pad"):
            attrs["pads"] = [int(v) for v in p["pad"]] * 2
        if pool == "avg":
            attrs["count_include_pad"] = \
                int(bool(p.get("count_include_pad", True)))
        return ex.emit("MaxPool" if pool == "max" else "AveragePool",
                       ins, [out], **attrs)
    if op_name == "BatchNorm":
        return ex.emit("BatchNormalization", ins, [out],
                       epsilon=float(p.get("eps", 1e-3)),
                       momentum=float(p.get("momentum", 0.9)))
    if op_name == "LayerNorm":
        return ex.emit("LayerNormalization", ins, [out],
                       epsilon=float(p.get("eps", 1e-5)),
                       axis=int(p.get("axis", -1)))
    if op_name == "InstanceNorm":
        return ex.emit("InstanceNormalization", ins, [out],
                       epsilon=float(p.get("eps", 1e-3)))
    if op_name == "L2Normalization":
        if p.get("mode", "instance") != "channel":
            raise MXNetError("ONNX export: L2Normalization mode=channel only")
        return ex.emit("LpNormalization", ins, [out], axis=1, p=2)
    if op_name == "Embedding":
        # ONNX Gather(weight, indices); mxnet Embedding(indices, weight)
        return ex.emit("Gather", [ins[1], ins[0]], [out], axis=0)
    if op_name == "take":
        return ex.emit("Gather", ins, [out], axis=int(p.get("axis", 0)))
    if op_name == "Dropout":
        # opset 12+ takes ratio as an input, not an attribute
        r = ex.const("ratio", _np.float32(p.get("p", 0.5)))
        return ex.emit("Dropout", [ins[0], r], [out])
    if op_name == "UpSampling":
        s = int(p.get("scale", 2))
        scales = ex.const("scales", _np.asarray([1, 1, s, s], _np.float32))
        roi = ex.const("roi", _np.asarray([], _np.float32))
        return ex.emit("Resize", [ins[0], roi, scales], [out],
                       mode="nearest")
    if op_name == "batch_dot":
        a, b = ins
        if p.get("transpose_a"):
            a = ex.emit("Transpose", [a], [ex.fresh("bt")], perm=[0, 2, 1])
        if p.get("transpose_b"):
            b = ex.emit("Transpose", [b], [ex.fresh("bt")], perm=[0, 2, 1])
        return ex.emit("MatMul", [a, b], [out])
    if op_name == "shape_array":
        ex.value_dtypes[out] = _TP.INT64
        return ex.emit("Shape", ins, [out])
    if op_name == "topk":
        if p.get("ret_typ", "indices") != "both":
            raise MXNetError("ONNX export: topk needs ret_typ='both'")
        kc = ex.const("k", _np.asarray([int(p.get("k", 1))], _np.int64))
        outs = [out, f"{out}__1"]
        ex.value_dtypes[outs[1]] = _TP.INT64  # TopK indices are int64
        ex.emit("TopK", [ins[0], kc], outs, axis=int(p.get("axis", -1)),
                largest=0 if p.get("is_ascend") else 1)
        return outs

    raise MXNetError(f"ONNX export: unsupported op {op_name}")


def export_op_names() -> List[str]:
    """MXNet op names the exporter understands (reference mx2onnx
    MXNetGraph.registered convert funcs)."""
    names = (set(_UNARY_EXPORT) | set(_BINARY_EXPORT) | set(_SCALAR_EXPORT)
             | set(_COMPARE_EXPORT) | set(_LOGICAL_EXPORT)
             | set(_REDUCE_EXPORT))
    names |= {
        "add_n", "broadcast_not_equal", "logical_not", "norm", "argmax",
        "argmin", "Reshape", "transpose", "expand_dims", "squeeze", "Concat",
        "stack", "slice", "slice_axis", "SliceChannel", "split", "tile",
        "pad", "clip", "Cast", "where", "broadcast_to", "depth_to_space",
        "space_to_depth", "zeros_like", "ones_like", "shape_array",
        "Activation",
        "LeakyReLU", "gelu", "silu", "hard_sigmoid", "softmax",
        "log_softmax", "FullyConnected", "Convolution", "Deconvolution",
        "Pooling", "BatchNorm", "LayerNorm", "InstanceNorm",
        "L2Normalization", "Embedding", "take", "Dropout", "UpSampling",
        "batch_dot", "topk",
        # round-5 parity additions (reference mx2onnx/_op_translations.py)
        "BlockGrad", "MakeLoss", "make_loss", "stop_gradient", "square",
        "size_array", "_maximum", "_minimum", "_power", "SoftmaxOutput",
        "LogisticRegressionOutput", "LRN", "Crop", "ROIPooling",
        "_linalg_gemm2", "linalg_gemm2", "_random_uniform", "_random_normal",
        "_random_uniform_like", "_random_normal_like", "_sample_multinomial",
        "Pad", "null",   # null = graph variable nodes, handled in export_model
    }
    return sorted(names)


def export_model(sym, params, input_shape: List[Tuple[int, ...]],
                 input_type=_np.float32, onnx_file_path: str = "model.onnx",
                 verbose: bool = False):
    """Export a Symbol + params to ONNX (reference
    contrib/onnx/mx2onnx/export_model.py export_model:31)."""
    from .. import symbol as sym_mod
    if isinstance(sym, str):
        sym = sym_mod.load(sym)
    if isinstance(params, str):
        from ..model import load_params
        arg, aux = load_params(params)
        params = {**arg, **aux}

    elem = _tp_of(input_type)
    ex = _Exporter(elem)
    value_names = {}           # id(node) -> onnx tensor name(s)
    inputs = []
    input_idx = 0
    for node in sym._topo():
        if node.kind == "var":
            value_names[id(node)] = node.name
            if node.is_rng():
                # executor RNG key feed — ONNX random generators own their
                # RNG, so the key is neither a graph input nor initializer
                continue
            if node.name in params:
                arr = params[node.name]
                np_arr = arr.asnumpy() if hasattr(arr, "asnumpy") else \
                    _np.asarray(arr)
                ex.initializers.append(_oh.make_tensor(
                    node.name, _tp_of(np_arr.dtype),
                    np_arr.shape, np_arr.flatten().tolist()))
            else:
                shape = input_shape[input_idx] \
                    if input_idx < len(input_shape) else None
                input_idx += 1
                inputs.append(_oh.make_tensor_value_info(
                    node.name, elem, list(shape) if shape else None))
            continue
        in_names = []
        for i, out_idx in node.inputs:
            v = value_names[id(i)]
            in_names.append(v[out_idx] if isinstance(v, (list, tuple)) else v)
        res = _export_node(ex, node.op.name, node.params, in_names, node.name)
        value_names[id(node)] = res

    def _head_name(n, out_idx):
        v = value_names[id(n)]
        return v[out_idx] if isinstance(v, (list, tuple)) else v

    out_infos = [
        _oh.make_tensor_value_info(
            _head_name(n, oi),
            ex.value_dtypes.get(_head_name(n, oi), elem), None)
        for n, oi in sym._heads]
    graph = _oh.make_graph(ex.nodes, "mxnet_tpu_model", inputs, out_infos,
                           initializer=ex.initializers)
    # opset 17: Squeeze/Unsqueeze/ReduceSum axes and Dropout ratio are
    # inputs (13+), GreaterOrEqual/LessOrEqual exist (12+), and
    # LayerNormalization is official (17) — the emitted node set is
    # conformant at exactly this version
    if _onnx is _shim:
        model = _oh.make_model(graph, producer_name="mxnet_tpu", opset=17)
    else:
        model = _oh.make_model(
            graph, producer_name="mxnet_tpu",
            opset_imports=[_oh.make_opsetid("", 17)])
    _onnx.save(model, onnx_file_path)
    return onnx_file_path


# ===========================================================================
# Import (onnx2mx)
# ===========================================================================

def _split_pads(at, ndim):
    """ONNX pads = [d1_begin..dn_begin, d1_end..dn_end]. Returns
    (symmetric_tuple, None) when begin == end, else (None, (begins, ends))
    so the caller can insert an explicit Pad."""
    pads = at.get("pads")
    if not pads:
        return (0,) * ndim, None
    begins = tuple(int(v) for v in pads[:ndim])
    ends = tuple(int(v) for v in pads[ndim:2 * ndim])
    if begins == ends:
        return begins, None
    return None, (begins, ends)


def _apply_pads(sym_mod, data_in, at, ndim, mode="constant"):
    """Resolve ONNX pads onto (possibly explicitly padded) input + a
    symmetric pad tuple for the op (shared by Conv and the pooling ops)."""
    sym_pad, asym = _split_pads(at, ndim)
    if asym is None:
        return data_in, sym_pad
    begins, ends = asym
    pw = (0, 0, 0, 0) + sum(zip(begins, ends), ())
    kwargs = {"constant_value": 0} if mode == "constant" else {}
    return (sym_mod.pad(data_in, mode=mode, pad_width=pw, **kwargs),
            (0,) * ndim)


def _node_attrs(node) -> Dict:
    if _onnx is _shim:
        return _shim.attr_dict(node)
    out = {}
    for a in node.attribute:
        out[a.name] = _oh.get_attribute_value(a)
        if isinstance(out[a.name], bytes):
            out[a.name] = out[a.name].decode()
    return out


# ONNX op -> mxnet sym unary function name
_UNARY_IMPORT = {
    "Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
    "Softplus": "softrelu", "Softsign": "softsign", "Exp": "exp",
    "Log": "log", "Sqrt": "sqrt", "Abs": "abs", "Neg": "negative",
    "Floor": "floor", "Ceil": "ceil", "Round": "round", "Sign": "sign",
    "Sin": "sin", "Cos": "cos", "Tan": "tan", "Asin": "arcsin",
    "Acos": "arccos", "Atan": "arctan", "Sinh": "sinh", "Cosh": "cosh",
    "Asinh": "arcsinh", "Acosh": "arccosh", "Atanh": "arctanh",
    "Erf": "erf", "Reciprocal": "reciprocal", "Identity": "identity",
    "Not": "logical_not",
}

_BINARY_IMPORT = {
    "Add": "broadcast_add", "Sub": "broadcast_sub", "Mul": "broadcast_mul",
    "Div": "broadcast_div", "Pow": "broadcast_power",
    "Equal": "broadcast_equal", "Greater": "broadcast_greater",
    "Less": "broadcast_lesser", "GreaterOrEqual": "broadcast_greater_equal",
    "LessOrEqual": "broadcast_lesser_equal",
    "And": "broadcast_logical_and", "Or": "broadcast_logical_or",
    "Xor": "broadcast_logical_xor",
}

# n-ary elementwise folds
_NARY_IMPORT = {"Max": "broadcast_maximum", "Min": "broadcast_minimum"}

_REDUCE_IMPORT = {"ReduceSum": "sum", "ReduceMean": "mean",
                  "ReduceMax": "max", "ReduceMin": "min",
                  "ReduceProd": "prod"}


def import_op_names() -> List[str]:
    """ONNX op types the importer understands (reference onnx2mx
    _convert_map in import_onnx.py)."""
    names = set(_UNARY_IMPORT) | set(_BINARY_IMPORT) | set(_NARY_IMPORT) \
        | set(_REDUCE_IMPORT)
    names |= {
        "Conv", "ConvTranspose", "Gemm", "MatMul", "LeakyRelu", "Elu",
        "Selu", "PRelu", "HardSigmoid", "Gelu", "MaxPool", "AveragePool",
        "GlobalAveragePool", "GlobalMaxPool", "BatchNormalization",
        "LayerNormalization", "InstanceNormalization", "LpNormalization",
        "Concat", "Sum", "Mean", "Reshape", "Flatten", "Softmax",
        "LogSoftmax", "Transpose", "Dropout", "Gather", "Clip", "Constant",
        "ConstantOfShape", "Range", "Squeeze", "Unsqueeze", "Slice",
        "Split", "Tile", "Pad", "Cast", "Where", "Expand", "Shape",
        "ArgMax", "ArgMin", "ReduceL2", "TopK", "Resize", "Upsample",
        "DepthToSpace", "SpaceToDepth",
        # round-5 parity additions (reference onnx2mx/_import_helper.py)
        "FC", "SpatialBN", "LRN", "MaxRoiPool", "GlobalLpPool", "LpPool",
        "Hardmax", "Multinomial", "RandomNormal", "RandomNormalLike",
        "RandomUniform", "RandomUniformLike", "ReduceL1", "ReduceLogSum",
        "ReduceLogSumExp", "ReduceSumSquare", "Size",
    }
    return sorted(names)


def import_model(model_file: str):
    """ONNX -> (sym, arg_params, aux_params) (reference
    contrib/onnx/onnx2mx/import_model.py import_model:29). Covers the op set
    produced by export_model plus the common elementwise/shape ops."""
    from .. import symbol as sym_mod
    from .. import ndarray as nd

    model = _onnx.load(model_file)
    graph = model.graph

    params: Dict[str, _np.ndarray] = {}
    for init in graph.initializer:
        params[init.name] = _to_array(init)

    env: Dict[str, object] = {}       # name -> Symbol
    aux_names = set()
    for vi in graph.input:
        if vi.name not in params:
            env[vi.name] = sym_mod.Variable(vi.name)
    for name in params:
        env[name] = sym_mod.Variable(name)

    def A(node):
        return _node_attrs(node)

    const_only = set()   # initializers consumed as shapes/axes/bounds
    tensor_used = set()  # initializers consumed as actual graph tensors
    shape_of: Dict[str, object] = {}  # Shape-node output -> source symbol

    def const_of(name):
        """Compile-time constant (shape/axes inputs must be initializers).
        Does NOT remove it — another node may share the same initializer;
        unused const-only entries are dropped after the walk."""
        if name in params:
            const_only.add(name)
            return params[name]
        raise MXNetError(f"ONNX import: input '{name}' must be a constant")

    def axes_of(node, at, idx=1):
        """Squeeze/Unsqueeze/ReduceSum axes: attr (opset <= 12) or
        constant input (opset 13)."""
        if "axes" in at:
            return [int(a) for a in at["axes"]]
        if len(node.input) > idx and node.input[idx]:
            return [int(a) for a in const_of(node.input[idx]).flatten()]
        return None

    def add_const_output(node, arr):
        pname = node.output[0]
        params[pname] = _np.asarray(arr)
        env[pname] = sym_mod.Variable(pname)

    for node in graph.node:
        ins = [env.get(i) for i in node.input]
        at = A(node)
        op = node.op_type
        out = None
        if op in _UNARY_IMPORT:
            out = getattr(sym_mod, _UNARY_IMPORT[op])(ins[0])
        elif op in _BINARY_IMPORT:
            out = getattr(sym_mod, _BINARY_IMPORT[op])(ins[0], ins[1])
        elif op in _NARY_IMPORT:
            fn = getattr(sym_mod, _NARY_IMPORT[op])
            out = ins[0]
            for nxt in ins[1:]:
                out = fn(out, nxt)
        elif op in _REDUCE_IMPORT:
            axes = axes_of(node, at)
            kw = {"keepdims": bool(at.get("keepdims", 1))}
            if axes is not None:
                kw["axis"] = tuple(axes)
            out = getattr(sym_mod, _REDUCE_IMPORT[op])(ins[0], **kw)
        elif op == "ReduceL2":
            axes = axes_of(node, at)
            kw = {"keepdims": bool(at.get("keepdims", 1)), "ord": 2}
            if axes is not None:
                kw["axis"] = tuple(axes)
            out = sym_mod.norm(ins[0], **kw)
        elif op in ("ArgMax", "ArgMin"):
            fn = sym_mod.argmax if op == "ArgMax" else sym_mod.argmin
            # ONNX ArgMax returns int64 — import with exact int indices
            # (int32 under the x32 policy); an exporter-appended Cast
            # restores the MXNet float contract on roundtrip
            out = fn(ins[0], axis=int(at.get("axis", 0)),
                     keepdims=bool(at.get("keepdims", 1)), dtype="int32")
        elif op == "Conv":
            k = at.get("kernel_shape", (3, 3))
            no_bias = len(node.input) < 3
            w = params.get(node.input[1])
            data_in, sym_pad = _apply_pads(sym_mod, ins[0], at, len(k))
            out = sym_mod.Convolution(
                data_in, env[node.input[1]],
                None if no_bias else env[node.input[2]],
                kernel=tuple(k),
                num_filter=int(w.shape[0]) if w is not None else 0,
                stride=tuple(at.get("strides", (1,) * len(k))),
                pad=sym_pad,
                dilate=tuple(at.get("dilations", (1,) * len(k))),
                num_group=int(at.get("group", 1)), no_bias=no_bias)
        elif op == "ConvTranspose":
            k = at.get("kernel_shape", (3, 3))
            no_bias = len(node.input) < 3
            w = params.get(node.input[1])
            sym_pad, asym = _split_pads(at, len(k))
            if asym is not None:
                raise MXNetError("ONNX import: asymmetric ConvTranspose pads")
            group = int(at.get("group", 1))
            out = sym_mod.Deconvolution(
                ins[0], env[node.input[1]],
                None if no_bias else env[node.input[2]],
                kernel=tuple(k),
                num_filter=int(w.shape[1]) * group if w is not None else 0,
                stride=tuple(at.get("strides", (1,) * len(k))),
                pad=sym_pad,
                dilate=tuple(at.get("dilations", (1,) * len(k))),
                adj=tuple(at["output_padding"]) if at.get("output_padding")
                else None,
                num_group=group, no_bias=no_bias)
        elif op == "Gemm":
            w = params.get(node.input[1])
            if w is None:
                # dynamic B (no initializer): FullyConnected's A.B^T contract
                # cannot absorb transB here — lower to matmul directly
                a_in = ins[0]
                if at.get("transA"):
                    a_in = sym_mod.transpose(a_in)
                b_in = ins[1]
                if at.get("transB"):
                    b_in = sym_mod.transpose(b_in)
                out = sym_mod._npi_matmul(a_in, b_in)
                alpha = float(at.get("alpha", 1.0))
                if alpha != 1.0:
                    out = out * alpha
                if len(node.input) > 2:
                    out = sym_mod.broadcast_add(
                        out, env[node.input[2]] * float(at.get("beta", 1.0)))
                for iname in node.input:
                    if iname in params and iname not in const_only:
                        tensor_used.add(iname)
                env[node.output[0]] = out
                continue
            num_hidden = int(w.shape[0] if at.get("transB")
                             else w.shape[1])
            alpha = float(at.get("alpha", 1.0))
            beta = float(at.get("beta", 1.0))
            a_in = ins[0]
            if at.get("transA"):
                a_in = sym_mod.transpose(a_in)
            w_sym = env[node.input[1]]
            if not at.get("transB") and w is not None:
                # FullyConnected expects (out, in). Materialize the
                # transposed weight under a fresh per-node name — mutating
                # the shared initializer in place would hand a second
                # consumer (tied weights, two Gemm nodes sharing B) a
                # double-transposed array.
                w_name = f"{node.input[1]}__T__{node.output[0]}"
                params[w_name] = _np.ascontiguousarray(w.T)
                w_sym = env.setdefault(w_name, sym_mod.Variable(w_name))
            has_c = len(node.input) > 2
            if alpha == 1.0 and beta == 1.0:
                out = sym_mod.FullyConnected(
                    a_in, w_sym,
                    env[node.input[2]] if has_c else None,
                    num_hidden=num_hidden, no_bias=not has_c)
            else:
                # alpha*A.B (+ beta*C): scale around a bias-free FC
                ab = sym_mod.FullyConnected(
                    a_in, w_sym, None,
                    num_hidden=num_hidden, no_bias=True)
                out = ab * alpha
                if has_c:
                    out = sym_mod.broadcast_add(
                        out, env[node.input[2]] * beta)
        elif op == "MatMul":
            # ONNX MatMul is np.matmul (batched for rank > 2)
            out = sym_mod._npi_matmul(ins[0], ins[1])
        elif op == "LeakyRelu":
            out = sym_mod.LeakyReLU(ins[0], act_type="leaky",
                                    slope=float(at.get("alpha", 0.01)))
        elif op == "Elu":
            out = sym_mod.LeakyReLU(ins[0], act_type="elu",
                                    slope=float(at.get("alpha", 1.0)))
        elif op == "Selu":
            out = sym_mod.LeakyReLU(ins[0], act_type="selu")
        elif op == "PRelu":
            # where(x > 0, x, slope * x) via relu(x) + slope * min(x, 0)
            neg = sym_mod.broadcast_minimum(ins[0],
                                            sym_mod.zeros_like(ins[0]))
            out = sym_mod.broadcast_add(
                sym_mod.relu(ins[0]), sym_mod.broadcast_mul(ins[1], neg))
        elif op == "HardSigmoid":
            out = sym_mod.hard_sigmoid(ins[0],
                                       alpha=float(at.get("alpha", 0.2)),
                                       beta=float(at.get("beta", 0.5)))
        elif op == "Gelu":
            out = sym_mod.gelu(
                ins[0], approximate=at.get("approximate", "none") == "tanh")
        elif op in ("MaxPool", "AveragePool"):
            k = at.get("kernel_shape", (2, 2))
            strides = tuple(at.get("strides", (1,) * len(k)))
            # ONNX default count_include_pad=0: padded cells are excluded
            # from the average's divisor
            incl = bool(at.get("count_include_pad", 0))
            if op == "MaxPool":
                # edge-padding is equivalent to ONNX's -inf pad for max
                data_in, sym_pad = _apply_pads(sym_mod, ins[0], at, len(k),
                                               mode="edge")
                out = sym_mod.Pooling(data_in, kernel=tuple(k),
                                      pool_type="max", stride=strides,
                                      pad=sym_pad)
            else:
                data_in, sym_pad = _apply_pads(sym_mod, ins[0], at, len(k))
                out = sym_mod.Pooling(
                    data_in, kernel=tuple(k), pool_type="avg",
                    stride=strides, pad=sym_pad,
                    count_include_pad=incl)
                if not incl and data_in is not ins[0]:
                    # explicit pre-pad hid the padding from the op: rebuild
                    # the exclude-pad divisor with a ones-mask pool
                    ones = sym_mod.ones_like(ins[0])
                    ones_p, _ = _apply_pads(sym_mod, ones, at, len(k))
                    cnt = sym_mod.Pooling(
                        ones_p, kernel=tuple(k), pool_type="avg",
                        stride=strides, pad=sym_pad,
                        count_include_pad=True)
                    out = sym_mod.broadcast_div(
                        sym_mod.Pooling(
                            data_in, kernel=tuple(k), pool_type="avg",
                            stride=strides, pad=sym_pad,
                            count_include_pad=True), cnt)
        elif op == "GlobalAveragePool":
            out = sym_mod.Pooling(ins[0], kernel=(1, 1), pool_type="avg",
                                  global_pool=True)
        elif op == "GlobalMaxPool":
            out = sym_mod.Pooling(ins[0], kernel=(1, 1), pool_type="max",
                                  global_pool=True)
        elif op in ("BatchNormalization", "SpatialBN"):
            out = sym_mod.BatchNorm(
                ins[0], env[node.input[1]], env[node.input[2]],
                env[node.input[3]], env[node.input[4]],
                eps=float(at.get("epsilon", 1e-5)),
                momentum=float(at.get("momentum", 0.9)),
                fix_gamma=False, use_global_stats=True)
            for aux in (node.input[3], node.input[4]):
                aux_names.add(aux)
        elif op == "LayerNormalization":
            out = sym_mod.LayerNorm(ins[0], env[node.input[1]],
                                    env[node.input[2]],
                                    eps=float(at.get("epsilon", 1e-5)),
                                    axis=int(at.get("axis", -1)))
        elif op == "InstanceNormalization":
            out = sym_mod.InstanceNorm(ins[0], env[node.input[1]],
                                       env[node.input[2]],
                                       eps=float(at.get("epsilon", 1e-5)))
        elif op == "LpNormalization":
            if int(at.get("p", 2)) != 2 or int(at.get("axis", -1)) != 1:
                raise MXNetError("ONNX import: LpNormalization p=2 axis=1 "
                                 "only")
            out = sym_mod.L2Normalization(ins[0], mode="channel")
        elif op == "Concat":
            out = sym_mod.Concat(*[env[i] for i in node.input],
                                 dim=int(at.get("axis", 1)))
        elif op == "Sum":
            out = sym_mod.add_n(*[env[i] for i in node.input])
        elif op == "Mean":
            out = sym_mod.add_n(*[env[i] for i in node.input]) \
                * (1.0 / len(node.input))
        elif op == "Reshape":
            shape = const_of(node.input[1]).astype(int).tolist()
            out = sym_mod.Reshape(ins[0], shape=tuple(shape))
        elif op == "Flatten":
            out = sym_mod.Flatten(ins[0])
        elif op == "Softmax":
            out = sym_mod.softmax(ins[0], axis=int(at.get("axis", -1)))
        elif op == "LogSoftmax":
            out = sym_mod.log_softmax(ins[0], axis=int(at.get("axis", -1)))
        elif op == "Transpose":
            perm = at.get("perm")
            out = sym_mod.transpose(ins[0],
                                    axes=tuple(perm) if perm else None)
        elif op == "Dropout":
            if len(node.input) > 1 and node.input[1]:   # opset 12+ input
                ratio = float(const_of(node.input[1]))
            else:
                ratio = float(at.get("ratio", 0.5))
            out = sym_mod.Dropout(ins[0], p=ratio)
        elif op == "Gather":
            axis = int(at.get("axis", 0))
            w = params.get(node.input[0])
            if axis == 0 and w is not None and w.ndim == 2:
                out = sym_mod.Embedding(
                    ins[1], env[node.input[0]],
                    input_dim=int(w.shape[0]), output_dim=int(w.shape[1]))
            else:
                # mode="wrap": ONNX Gather allows negative indices
                # (count from the end) — wrap is exactly that for the
                # valid [-n, n-1] range; clip would clamp -1 to 0
                out = sym_mod.take(ins[0], ins[1], axis=axis, mode="wrap")
        elif op == "Clip":
            # opset >= 11 passes bounds as inputs; opset <= 10 as the
            # 'min'/'max' node attributes (e.g. ReLU6 exports)
            lo = (float(const_of(node.input[1])) if len(node.input) > 1
                  and node.input[1] else at.get("min"))
            hi = (float(const_of(node.input[2])) if len(node.input) > 2
                  and node.input[2] else at.get("max"))
            lo = float(lo) if lo is not None else None
            hi = float(hi) if hi is not None else None
            out = sym_mod.clip(ins[0],
                               a_min=lo if lo is not None else -3.4e38,
                               a_max=hi if hi is not None else 3.4e38)
        elif op == "Squeeze":
            axes = axes_of(node, at)
            out = sym_mod.squeeze(
                ins[0], axis=tuple(axes) if axes is not None else None)
        elif op == "Unsqueeze":
            axes = axes_of(node, at)
            if not axes:
                raise MXNetError("ONNX import: Unsqueeze without axes")
            out = ins[0]
            for ax in sorted(axes):
                out = sym_mod.expand_dims(out, axis=ax)
        elif op == "Slice":
            if "starts" in at:  # opset <= 9: attribute form
                starts = [int(v) for v in at["starts"]]
                ends = [int(v) for v in at["ends"]]
                axes = [int(v) for v in at.get(
                    "axes", range(len(starts)))]
                steps = [1] * len(starts)
            else:
                starts = [int(v) for v in const_of(node.input[1]).flatten()]
                ends = [int(v) for v in const_of(node.input[2]).flatten()]
                axes = ([int(v) for v in const_of(node.input[3]).flatten()]
                        if len(node.input) > 3 and node.input[3]
                        else list(range(len(starts))))
                steps = ([int(v) for v in const_of(node.input[4]).flatten()]
                         if len(node.input) > 4 and node.input[4]
                         else [1] * len(starts))
            if any(s != 1 for s in steps):
                raise MXNetError("ONNX import: Slice steps != 1 unsupported")
            out = ins[0]
            for ax, st, en in zip(axes, starts, ends):
                out = sym_mod.slice_axis(
                    out, axis=ax, begin=st,
                    end=None if en >= (1 << 60) else en)
        elif op == "Split":
            axis = int(at.get("axis", 0))
            n_out = len(node.output)
            sizes = at.get("split")
            if sizes is None and len(node.input) > 1 and node.input[1]:
                sizes = [int(v) for v in const_of(node.input[1]).flatten()]
            if sizes is None or len(set(int(s) for s in sizes)) == 1:
                parts = sym_mod.SliceChannel(ins[0], num_outputs=n_out,
                                             axis=axis)
                out = list(parts) if isinstance(parts, (list, tuple)) \
                    else [parts[i] for i in range(n_out)]
            else:
                out, off = [], 0
                for s in sizes:
                    out.append(sym_mod.slice_axis(ins[0], axis=axis,
                                                  begin=off, end=off + int(s)))
                    off += int(s)
        elif op == "Tile":
            reps = [int(v) for v in const_of(node.input[1]).flatten()]
            out = sym_mod.tile(ins[0], reps=tuple(reps))
        elif op == "Pad":
            if "pads" in at:  # opset <= 10 attribute form
                pads = [int(v) for v in at["pads"]]
                value = float(at.get("value", 0.0))
            else:
                pads = [int(v) for v in const_of(node.input[1]).flatten()]
                value = (float(const_of(node.input[2]))
                         if len(node.input) > 2 and node.input[2] else 0.0)
            n = len(pads) // 2
            pw = sum(((pads[i], pads[n + i]) for i in range(n)), ())
            mode = at.get("mode", "constant")
            kw = {"constant_value": value} if mode == "constant" else {}
            out = sym_mod.pad(ins[0], mode=mode, pad_width=pw, **kw)
        elif op == "Cast":
            to = int(at.get("to", _TP.FLOAT))
            out = sym_mod.Cast(ins[0], dtype=_TP2NP.get(to, "float32"))
        elif op == "Where":
            out = sym_mod.where(ins[0], ins[1], ins[2])
        elif op == "Expand":
            shape = [int(v) for v in const_of(node.input[1]).flatten()]
            out = sym_mod.broadcast_to(ins[0], shape=tuple(shape))
        elif op == "Shape":
            out = sym_mod.shape_array(ins[0])
            shape_of[node.output[0]] = ins[0]
        elif op == "TopK":
            k = int(const_of(node.input[1]).flatten()[0]) \
                if len(node.input) > 1 else int(at.get("k", 1))
            out = sym_mod.topk(ins[0], k=k, axis=int(at.get("axis", -1)),
                               ret_typ="both",
                               is_ascend=not int(at.get("largest", 1)))
        elif op in ("Resize", "Upsample"):
            if op == "Resize" and len(node.input) >= 3 and node.input[2]:
                scales = const_of(node.input[2]).flatten()
            elif op == "Upsample" and len(node.input) >= 2 \
                    and node.input[1]:
                # opset-9 Upsample: scales is the 2nd input
                scales = const_of(node.input[1]).flatten()
            elif "scales" in at:   # opset-7 attribute form
                scales = _np.asarray(at["scales"], _np.float32)
            else:
                raise MXNetError("ONNX import: Resize needs scales")
            mode = at.get("mode", "nearest")
            if mode != "nearest":
                raise MXNetError("ONNX import: Resize mode=nearest only")
            s = float(scales[2])
            if scales[2] != scales[3] or s != int(s):
                raise MXNetError("ONNX import: Resize needs equal integer "
                                 "H/W scales")
            out = sym_mod.UpSampling(ins[0], scale=int(s),
                                     sample_type="nearest")
        elif op == "DepthToSpace":
            out = sym_mod.depth_to_space(ins[0],
                                         block_size=int(at["blocksize"]))
        elif op == "SpaceToDepth":
            out = sym_mod.space_to_depth(ins[0],
                                         block_size=int(at["blocksize"]))
        elif op == "Constant":
            val = at.get("value")
            # with pip onnx, get_attribute_value returns the TensorProto
            if not isinstance(val, _np.ndarray):
                val = _to_array(val)
            add_const_output(node, val)
            continue
        elif op == "ConstantOfShape":
            val = at.get("value")
            if val is not None and not isinstance(val, _np.ndarray):
                val = _to_array(val)
            fill = float(val.flatten()[0]) if val is not None else 0.0
            src = shape_of.get(node.input[0])
            if src is not None:
                # dynamic shape from a Shape node: this is the exporter's
                # zeros_like/ones_like encoding — lower back to it
                out = sym_mod.zeros_like(src) if fill == 0.0 \
                    else sym_mod.ones_like(src) * fill
            else:
                shape = [int(v) for v in const_of(node.input[0]).flatten()]
                dt = val.dtype if val is not None else _np.float32
                add_const_output(node, _np.full(shape, fill, dt))
                continue
        elif op == "Range":
            start, limit, delta = (const_of(n).flatten()[0]
                                   for n in node.input[:3])
            add_const_output(node, _np.arange(start, limit, delta))
            continue
        elif op == "FC":
            # pre-standard experimental op some legacy exporters emit
            w = params.get(node.input[1])
            has_c = len(node.input) > 2
            out = sym_mod.FullyConnected(
                ins[0], env[node.input[1]],
                env[node.input[2]] if has_c else None,
                num_hidden=int(w.shape[0]) if w is not None else 0,
                no_bias=not has_c)
        elif op == "LRN":
            out = sym_mod.LRN(ins[0], nsize=int(at.get("size", 5)),
                              alpha=float(at.get("alpha", 1e-4)),
                              beta=float(at.get("beta", 0.75)),
                              knorm=float(at.get("bias", 1.0)))
        elif op == "MaxRoiPool":
            out = sym_mod.ROIPooling(
                ins[0], ins[1],
                pooled_size=tuple(int(v) for v in at["pooled_shape"]),
                spatial_scale=float(at.get("spatial_scale", 1.0)))
        elif op == "GlobalLpPool":
            pv = int(at.get("p", 2))
            s = sym_mod.sum(sym_mod._power_scalar(sym_mod.abs(ins[0]),
                                                  scalar=float(pv)),
                            axis=(2, 3), keepdims=True)
            out = sym_mod._power_scalar(s, scalar=1.0 / pv)
        elif op == "LpPool":
            pv = int(at.get("p", 2))
            k = tuple(int(v) for v in at.get("kernel_shape", (2, 2)))
            strides = tuple(at.get("strides", (1,) * len(k)))
            xp = sym_mod._power_scalar(sym_mod.abs(ins[0]),
                                       scalar=float(pv))
            data_in, sym_pad = _apply_pads(sym_mod, xp, at, len(k))
            avg = sym_mod.Pooling(data_in, kernel=k, pool_type="avg",
                                  stride=strides, pad=sym_pad,
                                  count_include_pad=True)
            win = 1
            for v in k:
                win *= int(v)
            out = sym_mod._power_scalar(
                sym_mod._mul_scalar(avg, scalar=float(win)),
                scalar=1.0 / pv)
        elif op == "Hardmax":
            ax = int(at.get("axis", -1))
            mx_ = sym_mod.max(ins[0], axis=ax, keepdims=True)
            eq = sym_mod.broadcast_equal(ins[0], mx_)
            # first-occurrence tie-break: cumsum of the hit mask is exactly
            # 1 at the first max and >1 at every later tie
            first = sym_mod._equal_scalar(sym_mod.cumsum(eq, axis=ax),
                                          scalar=1.0)
            out = sym_mod.elemwise_mul(eq, first)
        elif op == "Multinomial":
            # ONNX input is unnormalized log-probs; our sampler takes
            # probability rows — softmax bridges exactly
            n = int(at.get("sample_size", 1))
            probs = sym_mod.softmax(ins[0], axis=-1)
            out = sym_mod._sample_multinomial(
                probs, shape=n,
                dtype="int64" if int(at.get("dtype", _TP.INT32)) == _TP.INT64
                else "int32")
        elif op in ("RandomNormal", "RandomUniform"):
            shape = tuple(int(v) for v in at.get("shape", (1,)))
            dt = _TP2NP.get(int(at.get("dtype", _TP.FLOAT)), "float32")
            if op == "RandomNormal":
                out = sym_mod._random_normal(
                    loc=float(at.get("mean", 0.0)),
                    scale=float(at.get("scale", 1.0)), shape=shape, dtype=dt)
            else:
                out = sym_mod._random_uniform(
                    low=float(at.get("low", 0.0)),
                    high=float(at.get("high", 1.0)), shape=shape, dtype=dt)
        elif op == "RandomNormalLike":
            out = sym_mod._random_normal_like(
                ins[0], loc=float(at.get("mean", 0.0)),
                scale=float(at.get("scale", 1.0)))
        elif op == "RandomUniformLike":
            out = sym_mod._random_uniform_like(
                ins[0], low=float(at.get("low", 0.0)),
                high=float(at.get("high", 1.0)))
        elif op in ("ReduceL1", "ReduceLogSum", "ReduceLogSumExp",
                    "ReduceSumSquare"):
            axes = axes_of(node, at)
            kw = {"keepdims": bool(at.get("keepdims", 1))}
            if axes is not None:
                kw["axis"] = tuple(axes)
            if op == "ReduceL1":
                out = sym_mod.sum(sym_mod.abs(ins[0]), **kw)
            elif op == "ReduceLogSum":
                out = sym_mod.log(sym_mod.sum(ins[0], **kw))
            elif op == "ReduceLogSumExp":
                out = sym_mod.log(sym_mod.sum(sym_mod.exp(ins[0]), **kw))
            else:
                out = sym_mod.sum(sym_mod.square(ins[0]), **kw)
        elif op == "Size":
            out = sym_mod.size_array(ins[0])
        else:
            raise MXNetError(f"ONNX import: unsupported op {op}")
        for iname in node.input:
            if iname in params and iname not in const_only:
                tensor_used.add(iname)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for oname, osym in zip(node.output, outs):
            env[oname] = osym

    heads = [env[vo.name] for vo in graph.output]
    sym = heads[0] if len(heads) == 1 else sym_mod.Group(heads)

    arg_params, aux_params = {}, {}
    graph_inputs = set(sym.list_inputs())
    for name, arr in params.items():
        if name in const_only and name not in tensor_used:
            continue  # shape/axes-only initializer, not a graph tensor
        if name not in graph_inputs:
            # initializer superseded during import (e.g. a Gemm transB=0
            # weight replaced by its __T__ transposed copy) — dropping it
            # keeps arg_params exactly the bindable set
            continue
        target = aux_params if name in aux_names else arg_params
        target[name] = nd.array(arr)
    return sym, arg_params, aux_params


def _to_array(tensor) -> _np.ndarray:
    return _onh.to_array(tensor)  # shim or pip onnx — aliased at import


def get_model_metadata(model_file: str):
    """Input/output names+shapes of an ONNX file (reference
    contrib/onnx/onnx2mx/import_model.py get_model_metadata:60)."""
    model = _onnx.load(model_file)
    graph = model.graph
    inits = {i.name for i in graph.initializer}

    def info(vi):
        dims = tuple(
            (d.dim_value if d.HasField("dim_value") else None)
            if hasattr(d, "HasField") else d.dim_value
            for d in vi.type.tensor_type.shape.dim)
        return (vi.name, dims)

    return {
        "input_tensor_data": [info(v) for v in graph.input
                              if v.name not in inits],
        "output_tensor_data": [info(v) for v in graph.output],
    }
