"""ONNX interop (reference python/mxnet/contrib/onnx/).

The `onnx` package is not part of this environment, so export/import are
gated: when onnx IS installed, export_model serializes a Symbol graph to an
ONNX ModelProto covering the common layer ops; without it, both entry points
raise with a pointer to the portable alternative (HybridBlock.export /
Symbol JSON + params — loadable by any mxnet_tpu build).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as _np

from ..base import MXNetError

try:
    import onnx as _onnx
    from onnx import helper as _oh, TensorProto as _TP
    _HAS_ONNX = True
except ImportError:
    _HAS_ONNX = False


_OP_MAP = {
    # mxnet op -> (onnx op, attr translator)
    "FullyConnected": "Gemm",
    "Convolution": "Conv",
    "Activation": None,  # dispatched on act_type
    "relu": "Relu",
    "sigmoid": "Sigmoid",
    "tanh": "Tanh",
    "softmax": "Softmax",
    "Pooling": None,     # Max/AveragePool
    "BatchNorm": "BatchNormalization",
    "Flatten": "Flatten",
    "Reshape": "Reshape",
    "Concat": "Concat",
    "elemwise_add": "Add",
    "broadcast_add": "Add",
    "elemwise_mul": "Mul",
    "broadcast_mul": "Mul",
    "Dropout": "Dropout",
    "LayerNorm": "LayerNormalization",
    "Embedding": "Gather",
    "transpose": "Transpose",
}


def _require_onnx():
    if not _HAS_ONNX:
        raise MXNetError(
            "the 'onnx' package is not installed in this environment; for a "
            "portable serialized model use HybridBlock.export() (symbol JSON "
            "+ params) or model.save_checkpoint()")


def export_model(sym, params, input_shape: List[Tuple[int, ...]],
                 input_type=_np.float32, onnx_file_path: str = "model.onnx",
                 verbose: bool = False):
    """Export a Symbol + params to ONNX (reference
    contrib/onnx/mx2onnx/export_model.py). Requires the onnx package."""
    _require_onnx()
    from .. import symbol as sym_mod
    if isinstance(sym, str):
        sym = sym_mod.load(sym)
    if isinstance(params, str):
        from ..model import load_params
        arg, aux = load_params(params)
        params = {**arg, **aux}

    nodes, initializers, value_infos = [], [], []
    topo = sym._topo()
    names = {}
    dtype_map = {_np.float32: _TP.FLOAT, _np.float64: _TP.DOUBLE,
                 _np.int32: _TP.INT32, _np.int64: _TP.INT64}
    elem = dtype_map.get(_np.dtype(input_type).type, _TP.FLOAT)
    inputs = []
    input_idx = 0
    for node in topo:
        if node.kind == "var":
            names[id(node)] = node.name
            if node.name in params:
                arr = params[node.name]
                np_arr = arr.asnumpy() if hasattr(arr, "asnumpy") else \
                    _np.asarray(arr)
                initializers.append(_oh.make_tensor(
                    node.name, dtype_map.get(np_arr.dtype.type, _TP.FLOAT),
                    np_arr.shape, np_arr.flatten().tolist()))
            else:
                shape = input_shape[input_idx] \
                    if input_idx < len(input_shape) else None
                input_idx += 1
                inputs.append(_oh.make_tensor_value_info(
                    node.name, elem, list(shape) if shape else None))
            continue
        op_name = node.op.name
        onnx_op = _OP_MAP.get(op_name)
        if op_name == "Activation":
            onnx_op = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
                       "softrelu": "Softplus"}.get(
                           node.params.get("act_type", "relu"), "Relu")
        elif op_name == "Pooling":
            onnx_op = "MaxPool" if node.params.get("pool_type", "max") == "max" \
                else "AveragePool"
        if onnx_op is None:
            raise MXNetError(f"ONNX export: unsupported op {op_name}")
        out_name = node.name
        names[id(node)] = out_name
        in_names = [names[id(i)] for i, _ in node.inputs]
        attrs = _attrs_for(op_name, node.params)
        nodes.append(_oh.make_node(onnx_op, in_names, [out_name],
                                   name=node.name, **attrs))
    out_infos = [_oh.make_tensor_value_info(names[id(n)], elem, None)
                 for n, _ in sym._heads]
    graph = _oh.make_graph(nodes, "mxnet_tpu_model", inputs, out_infos,
                           initializer=initializers)
    model = _oh.make_model(graph, producer_name="mxnet_tpu")
    _onnx.save(model, onnx_file_path)
    return onnx_file_path


def _attrs_for(op_name: str, p: Dict) -> Dict:
    if op_name == "Convolution":
        k = tuple(p.get("kernel", ()))
        out = {"kernel_shape": list(k)}
        if p.get("stride"):
            out["strides"] = list(p["stride"])
        if p.get("pad"):
            out["pads"] = list(p["pad"]) * 2
        if p.get("num_group", 1) != 1:
            out["group"] = int(p["num_group"])
        return out
    if op_name == "Pooling":
        out = {"kernel_shape": list(p.get("kernel", (1, 1)))}
        if p.get("stride"):
            out["strides"] = list(p["stride"])
        if p.get("pad"):
            out["pads"] = list(p["pad"]) * 2
        return out
    if op_name == "Concat":
        return {"axis": int(p.get("dim", 1))}
    if op_name == "softmax":
        return {"axis": int(p.get("axis", -1))}
    if op_name == "BatchNorm":
        return {"epsilon": float(p.get("eps", 1e-3)),
                "momentum": float(p.get("momentum", 0.9))}
    if op_name == "transpose":
        return {"perm": list(p.get("axes", ()))} if p.get("axes") else {}
    if op_name == "FullyConnected":
        return {"transB": 1}
    return {}


def import_model(model_file: str):
    """ONNX -> (sym, arg_params, aux_params) (reference
    contrib/onnx/onnx2mx/import_model.py). Requires the onnx package."""
    _require_onnx()
    raise MXNetError("ONNX import is not implemented yet; export the source "
                     "model with HybridBlock.export-compatible tooling")
