"""ONNX interop (reference python/mxnet/contrib/onnx/ — mx2onnx export +
onnx2mx import).

Self-contained: when the `onnx` pip package is installed it is used
directly; otherwise serialization falls back to the vendored protobuf
subset in `onnx_proto/` (same wire format — files interchange with stock
onnx/onnxruntime). Both `export_model` and `import_model` therefore always
work, unlike the reference which hard-requires the pip package.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as _np

from ..base import MXNetError
from . import onnx_proto as _shim

try:
    import onnx as _onnx
    from onnx import helper as _oh, TensorProto as _TP
    from onnx import numpy_helper as _onh
except ImportError:
    # the vendored subset serves the same API surface
    _onnx, _oh, _TP, _onh = _shim, _shim.helper, _shim.TensorProto, \
        _shim.numpy_helper


_OP_MAP = {
    # mxnet op -> (onnx op, attr translator)
    "FullyConnected": "Gemm",
    "Convolution": "Conv",
    "Activation": None,  # dispatched on act_type
    "relu": "Relu",
    "sigmoid": "Sigmoid",
    "tanh": "Tanh",
    "softmax": "Softmax",
    "Pooling": None,     # Max/AveragePool
    "BatchNorm": "BatchNormalization",
    "Flatten": "Flatten",
    "Reshape": "Reshape",
    "Concat": "Concat",
    "elemwise_add": "Add",
    "broadcast_add": "Add",
    "elemwise_mul": "Mul",
    "broadcast_mul": "Mul",
    "Dropout": "Dropout",
    "LayerNorm": "LayerNormalization",
    "Embedding": "Gather",
    "transpose": "Transpose",
}


def export_model(sym, params, input_shape: List[Tuple[int, ...]],
                 input_type=_np.float32, onnx_file_path: str = "model.onnx",
                 verbose: bool = False):
    """Export a Symbol + params to ONNX (reference
    contrib/onnx/mx2onnx/export_model.py). Requires the onnx package."""
    from .. import symbol as sym_mod
    if isinstance(sym, str):
        sym = sym_mod.load(sym)
    if isinstance(params, str):
        from ..model import load_params
        arg, aux = load_params(params)
        params = {**arg, **aux}

    nodes, initializers, value_infos = [], [], []
    topo = sym._topo()
    names = {}
    dtype_map = {_np.float32: _TP.FLOAT, _np.float64: _TP.DOUBLE,
                 _np.int32: _TP.INT32, _np.int64: _TP.INT64}
    elem = dtype_map.get(_np.dtype(input_type).type, _TP.FLOAT)
    inputs = []
    input_idx = 0
    for node in topo:
        if node.kind == "var":
            names[id(node)] = node.name
            if node.name in params:
                arr = params[node.name]
                np_arr = arr.asnumpy() if hasattr(arr, "asnumpy") else \
                    _np.asarray(arr)
                initializers.append(_oh.make_tensor(
                    node.name, dtype_map.get(np_arr.dtype.type, _TP.FLOAT),
                    np_arr.shape, np_arr.flatten().tolist()))
            else:
                shape = input_shape[input_idx] \
                    if input_idx < len(input_shape) else None
                input_idx += 1
                inputs.append(_oh.make_tensor_value_info(
                    node.name, elem, list(shape) if shape else None))
            continue
        op_name = node.op.name
        onnx_op = _OP_MAP.get(op_name)
        if op_name == "Activation":
            onnx_op = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
                       "softrelu": "Softplus"}.get(
                           node.params.get("act_type", "relu"), "Relu")
        elif op_name == "Pooling":
            onnx_op = "MaxPool" if node.params.get("pool_type", "max") == "max" \
                else "AveragePool"
        if onnx_op is None:
            raise MXNetError(f"ONNX export: unsupported op {op_name}")
        out_name = node.name
        names[id(node)] = out_name
        in_names = [names[id(i)] for i, _ in node.inputs]
        attrs = _attrs_for(op_name, node.params)
        nodes.append(_oh.make_node(onnx_op, in_names, [out_name],
                                   name=node.name, **attrs))
    out_infos = [_oh.make_tensor_value_info(names[id(n)], elem, None)
                 for n, _ in sym._heads]
    graph = _oh.make_graph(nodes, "mxnet_tpu_model", inputs, out_infos,
                           initializer=initializers)
    model = _oh.make_model(graph, producer_name="mxnet_tpu")
    _onnx.save(model, onnx_file_path)
    return onnx_file_path


def _attrs_for(op_name: str, p: Dict) -> Dict:
    if op_name == "Convolution":
        k = tuple(p.get("kernel", ()))
        out = {"kernel_shape": list(k)}
        if p.get("stride"):
            out["strides"] = list(p["stride"])
        if p.get("pad"):
            out["pads"] = list(p["pad"]) * 2
        if p.get("num_group", 1) != 1:
            out["group"] = int(p["num_group"])
        return out
    if op_name == "Pooling":
        out = {"kernel_shape": list(p.get("kernel", (1, 1)))}
        if p.get("stride"):
            out["strides"] = list(p["stride"])
        if p.get("pad"):
            out["pads"] = list(p["pad"]) * 2
        return out
    if op_name == "Concat":
        return {"axis": int(p.get("dim", 1))}
    if op_name == "softmax":
        return {"axis": int(p.get("axis", -1))}
    if op_name == "BatchNorm":
        return {"epsilon": float(p.get("eps", 1e-3)),
                "momentum": float(p.get("momentum", 0.9))}
    if op_name == "transpose":
        return {"perm": list(p.get("axes", ()))} if p.get("axes") else {}
    if op_name == "FullyConnected":
        return {"transB": 1}
    return {}


def _split_pads(at, ndim):
    """ONNX pads = [d1_begin..dn_begin, d1_end..dn_end]. Returns
    (symmetric_tuple, None) when begin == end, else (None, (begins, ends))
    so the caller can insert an explicit Pad."""
    pads = at.get("pads")
    if not pads:
        return (0,) * ndim, None
    begins = tuple(int(v) for v in pads[:ndim])
    ends = tuple(int(v) for v in pads[ndim:2 * ndim])
    if begins == ends:
        return begins, None
    return None, (begins, ends)


def _apply_pads(sym_mod, data_in, at, ndim, mode="constant"):
    """Resolve ONNX pads onto (possibly explicitly padded) input + a
    symmetric pad tuple for the op (shared by Conv and the pooling ops)."""
    sym_pad, asym = _split_pads(at, ndim)
    if asym is None:
        return data_in, sym_pad
    begins, ends = asym
    pw = (0, 0, 0, 0) + sum(zip(begins, ends), ())
    kwargs = {"constant_value": 0} if mode == "constant" else {}
    return (sym_mod.pad(data_in, mode=mode, pad_width=pw, **kwargs),
            (0,) * ndim)


def _node_attrs(node) -> Dict:
    if _onnx is _shim:
        return _shim.attr_dict(node)
    out = {}
    for a in node.attribute:
        out[a.name] = _oh.get_attribute_value(a)
        if isinstance(out[a.name], bytes):
            out[a.name] = out[a.name].decode()
    return out


def import_model(model_file: str):
    """ONNX -> (sym, arg_params, aux_params) (reference
    contrib/onnx/onnx2mx/import_model.py import_model:29). Covers the op set
    produced by export_model plus the common elementwise/shape ops."""
    from .. import symbol as sym_mod
    from .. import ndarray as nd

    model = _onnx.load(model_file)
    graph = model.graph

    params: Dict[str, _np.ndarray] = {}
    for init in graph.initializer:
        params[init.name] = _to_array(init)

    env: Dict[str, object] = {}       # name -> Symbol
    aux_names = set()
    for vi in graph.input:
        if vi.name not in params:
            env[vi.name] = sym_mod.Variable(vi.name)
    for name in params:
        env[name] = sym_mod.Variable(name)

    def A(node):
        return _node_attrs(node)

    const_only = set()   # initializers consumed as shapes/axes/bounds
    tensor_used = set()  # initializers consumed as actual graph tensors

    def const_of(name):
        """Compile-time constant (shape/axes inputs must be initializers).
        Does NOT remove it — another node may share the same initializer;
        unused const-only entries are dropped after the walk."""
        if name in params:
            const_only.add(name)
            return params[name]
        raise MXNetError(f"ONNX import: input '{name}' must be a constant")

    for node in graph.node:
        ins = [env.get(i) for i in node.input]
        at = A(node)
        op = node.op_type
        out = None
        if op == "Conv":
            k = at.get("kernel_shape", (3, 3))
            no_bias = len(node.input) < 3
            w = params.get(node.input[1])
            data_in, sym_pad = _apply_pads(sym_mod, ins[0], at, len(k))
            out = sym_mod.Convolution(
                data_in, env[node.input[1]],
                None if no_bias else env[node.input[2]],
                kernel=tuple(k), num_filter=int(w.shape[0]) if w is not None else 0,
                stride=tuple(at.get("strides", (1,) * len(k))),
                pad=sym_pad,
                dilate=tuple(at.get("dilations", (1,) * len(k))),
                num_group=int(at.get("group", 1)), no_bias=no_bias)
        elif op == "Gemm":
            w = params.get(node.input[1])
            if w is None:
                num_hidden = 0
            else:
                num_hidden = int(w.shape[0] if at.get("transB") else w.shape[1])
            alpha = float(at.get("alpha", 1.0))
            beta = float(at.get("beta", 1.0))
            a_in = ins[0]
            if at.get("transA"):
                a_in = sym_mod.transpose(a_in)
            w_sym = env[node.input[1]]
            if not at.get("transB") and w is not None:
                # FullyConnected expects (out, in). Materialize the
                # transposed weight under a fresh per-node name — mutating
                # the shared initializer in place would hand a second
                # consumer (tied weights, two Gemm nodes sharing B) a
                # double-transposed array.
                w_name = f"{node.input[1]}__T__{node.output[0]}"
                params[w_name] = _np.ascontiguousarray(w.T)
                w_sym = env.setdefault(w_name, sym_mod.Variable(w_name))
            has_c = len(node.input) > 2
            if alpha == 1.0 and beta == 1.0:
                out = sym_mod.FullyConnected(
                    a_in, w_sym,
                    env[node.input[2]] if has_c else None,
                    num_hidden=num_hidden, no_bias=not has_c)
            else:
                # alpha*A.B (+ beta*C): scale around a bias-free FC
                ab = sym_mod.FullyConnected(
                    a_in, w_sym, None,
                    num_hidden=num_hidden, no_bias=True)
                out = ab * alpha
                if has_c:
                    out = sym_mod.broadcast_add(
                        out, env[node.input[2]] * beta)
        elif op == "MatMul":
            out = sym_mod.dot(ins[0], ins[1])
        elif op in ("Relu", "Sigmoid", "Tanh", "Softplus"):
            act = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
                   "Softplus": "softrelu"}[op]
            out = sym_mod.Activation(ins[0], act_type=act)
        elif op == "LeakyRelu":
            out = sym_mod.LeakyReLU(ins[0], act_type="leaky",
                                    slope=float(at.get("alpha", 0.01)))
        elif op in ("MaxPool", "AveragePool"):
            k = at.get("kernel_shape", (2, 2))
            strides = tuple(at.get("strides", (1,) * len(k)))
            # ONNX default count_include_pad=0: padded cells are excluded
            # from the average's divisor
            incl = bool(at.get("count_include_pad", 0))
            if op == "MaxPool":
                # edge-padding is equivalent to ONNX's -inf pad for max
                data_in, sym_pad = _apply_pads(sym_mod, ins[0], at, len(k),
                                               mode="edge")
                out = sym_mod.Pooling(data_in, kernel=tuple(k),
                                      pool_type="max", stride=strides,
                                      pad=sym_pad)
            else:
                data_in, sym_pad = _apply_pads(sym_mod, ins[0], at, len(k))
                out = sym_mod.Pooling(
                    data_in, kernel=tuple(k), pool_type="avg",
                    stride=strides, pad=sym_pad,
                    count_include_pad=incl)
                if not incl and data_in is not ins[0]:
                    # explicit pre-pad hid the padding from the op: rebuild
                    # the exclude-pad divisor with a ones-mask pool
                    ones = sym_mod.ones_like(ins[0])
                    ones_p, _ = _apply_pads(sym_mod, ones, at, len(k))
                    cnt = sym_mod.Pooling(
                        ones_p, kernel=tuple(k), pool_type="avg",
                        stride=strides, pad=sym_pad,
                        count_include_pad=True)
                    out = sym_mod.broadcast_div(
                        sym_mod.Pooling(
                            data_in, kernel=tuple(k), pool_type="avg",
                            stride=strides, pad=sym_pad,
                            count_include_pad=True), cnt)
        elif op == "GlobalAveragePool":
            out = sym_mod.Pooling(ins[0], kernel=(1, 1), pool_type="avg",
                                  global_pool=True)
        elif op == "BatchNormalization":
            out = sym_mod.BatchNorm(
                ins[0], env[node.input[1]], env[node.input[2]],
                env[node.input[3]], env[node.input[4]],
                eps=float(at.get("epsilon", 1e-5)),
                momentum=float(at.get("momentum", 0.9)),
                fix_gamma=False, use_global_stats=True)
            for aux in (node.input[3], node.input[4]):
                aux_names.add(aux)
        elif op == "LayerNormalization":
            out = sym_mod.LayerNorm(ins[0], env[node.input[1]],
                                    env[node.input[2]],
                                    eps=float(at.get("epsilon", 1e-5)),
                                    axis=int(at.get("axis", -1)))
        elif op == "Concat":
            out = sym_mod.Concat(*[env[i] for i in node.input],
                                 num_args=len(node.input),
                                 dim=int(at.get("axis", 1)))
        elif op in ("Add", "Sub", "Mul", "Div"):
            fn = {"Add": sym_mod.broadcast_add, "Sub": sym_mod.broadcast_sub,
                  "Mul": sym_mod.broadcast_mul, "Div": sym_mod.broadcast_div}
            out = fn[op](ins[0], ins[1])
        elif op == "Sum":
            out = sym_mod.add_n(*[env[i] for i in node.input])
        elif op == "Reshape":
            shape = const_of(node.input[1]).astype(int).tolist()
            out = sym_mod.Reshape(ins[0], shape=tuple(shape))
        elif op == "Flatten":
            out = sym_mod.Flatten(ins[0])
        elif op == "Softmax":
            out = sym_mod.softmax(ins[0], axis=int(at.get("axis", -1)))
        elif op == "Transpose":
            perm = at.get("perm")
            out = sym_mod.transpose(ins[0],
                                    axes=tuple(perm) if perm else None)
        elif op == "Dropout":
            out = sym_mod.Dropout(ins[0], p=float(at.get("ratio", 0.5)))
        elif op == "Identity":
            out = sym_mod.identity(ins[0])
        elif op == "Gather":
            if int(at.get("axis", 0)) != 0:
                raise MXNetError("ONNX import: Gather supports axis=0 only "
                                 "(Embedding-style lookup)")
            w = params.get(node.input[0])
            out = sym_mod.Embedding(
                ins[1], env[node.input[0]],
                input_dim=int(w.shape[0]) if w is not None else 0,
                output_dim=int(w.shape[1]) if w is not None else 0)
        elif op == "Clip":
            # opset >= 11 passes bounds as inputs; opset <= 10 as the
            # 'min'/'max' node attributes (e.g. ReLU6 exports)
            lo = (float(const_of(node.input[1])) if len(node.input) > 1
                  and node.input[1] else at.get("min"))
            hi = (float(const_of(node.input[2])) if len(node.input) > 2
                  and node.input[2] else at.get("max"))
            lo = float(lo) if lo is not None else None
            hi = float(hi) if hi is not None else None
            out = sym_mod.clip(ins[0], a_min=lo if lo is not None else -3.4e38,
                               a_max=hi if hi is not None else 3.4e38)
        elif op in ("Exp", "Log", "Sqrt", "Abs", "Neg", "Floor", "Ceil"):
            out = getattr(sym_mod, op.lower())(ins[0])
        elif op == "Constant":
            val = at.get("value")
            # with pip onnx, get_attribute_value returns the TensorProto
            if not isinstance(val, _np.ndarray):
                val = _to_array(val)
            pname = node.output[0]
            params[pname] = _np.asarray(val)
            env[pname] = sym_mod.Variable(pname)
            continue
        else:
            raise MXNetError(f"ONNX import: unsupported op {op}")
        for iname in node.input:
            if iname in params and iname not in const_only:
                tensor_used.add(iname)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for oname, osym in zip(node.output, outs):
            env[oname] = osym

    heads = [env[vo.name] for vo in graph.output]
    sym = heads[0] if len(heads) == 1 else sym_mod.Group(heads)

    arg_params, aux_params = {}, {}
    graph_inputs = set(sym.list_inputs())
    for name, arr in params.items():
        if name in const_only and name not in tensor_used:
            continue  # shape/axes-only initializer, not a graph tensor
        if name not in graph_inputs:
            # initializer superseded during import (e.g. a Gemm transB=0
            # weight replaced by its __T__ transposed copy) — dropping it
            # keeps arg_params exactly the bindable set
            continue
        target = aux_params if name in aux_names else arg_params
        target[name] = nd.array(arr)
    return sym, arg_params, aux_params


def _to_array(tensor) -> _np.ndarray:
    return _onh.to_array(tensor)  # shim or pip onnx — aliased at import


def get_model_metadata(model_file: str):
    """Input/output names+shapes of an ONNX file (reference
    contrib/onnx/onnx2mx/import_model.py get_model_metadata:60)."""
    model = _onnx.load(model_file)
    graph = model.graph
    inits = {i.name for i in graph.initializer}

    def info(vi):
        dims = tuple(
            (d.dim_value if d.HasField("dim_value") else None)
            if hasattr(d, "HasField") else d.dim_value
            for d in vi.type.tensor_type.shape.dim)
        return (vi.name, dims)

    return {
        "input_tensor_data": [info(v) for v in graph.input
                              if v.name not in inits],
        "output_tensor_data": [info(v) for v in graph.output],
    }
