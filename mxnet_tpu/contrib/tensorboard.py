"""TensorBoard logging (reference python/mxnet/contrib/tensorboard.py
LogMetricsCallback). Writes TensorBoard-compatible scalar event files
directly (tfevents protobuf framing with CRC32C) — no tensorboard package
required to WRITE; any TensorBoard install can read the logs.
"""
from __future__ import annotations

import os
import struct
import time
from typing import Optional


def _masked_crc32c(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xa282ead8 & 0xFFFFFFFF


_CRC_TABLE = []


def _crc32c(data: bytes) -> int:
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _varint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            out += bytes([b])
            return out


def _field(num: int, wire: int) -> bytes:
    return _varint((num << 3) | wire)


def _scalar_event(tag: str, value: float, step: int, wall: float) -> bytes:
    """Hand-rolled Event{wall_time, step, summary{value{tag, simple_value}}}
    protobuf (schema: tensorboard event.proto / summary.proto)."""
    tag_b = tag.encode()
    sv = _field(1, 2) + _varint(len(tag_b)) + tag_b \
        + _field(2, 5) + struct.pack("<f", float(value))
    summary = _field(1, 2) + _varint(len(sv)) + sv
    ev = _field(1, 1) + struct.pack("<d", wall) \
        + _field(2, 0) + _varint(step) \
        + _field(5, 2) + _varint(len(summary)) + summary
    return ev


class SummaryWriter:
    """Minimal event-file writer (scalar support)."""

    def __init__(self, logdir: str):
        os.makedirs(logdir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.mxnet_tpu"
        self._f = open(os.path.join(logdir, fname), "wb")
        self._write_event(self._version_event())

    def _version_event(self) -> bytes:
        v = b"brain.Event:2"
        return _field(1, 1) + struct.pack("<d", time.time()) \
            + _field(3, 2) + _varint(len(v)) + v

    def _write_event(self, payload: bytes):
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc32c(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc32c(payload)))
        self._f.flush()

    def add_scalar(self, tag: str, value: float, global_step: int = 0):
        self._write_event(_scalar_event(tag, value, global_step, time.time()))

    def close(self):
        self._f.close()


class LogMetricsCallback:
    """Batch-end callback streaming metric values to TensorBoard
    (reference contrib/tensorboard.py LogMetricsCallback)."""

    def __init__(self, logging_dir: str, prefix: Optional[str] = None):
        self.prefix = prefix
        self._writer = SummaryWriter(logging_dir)
        self._step = 0

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self._step += 1
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = f"{self.prefix}-{name}"
            self._writer.add_scalar(name, value, self._step)
