"""Self-contained ONNX serialization shim.

Exposes the slice of the `onnx` package API that mx.contrib.onnx uses
(`load`/`save`, `helper.make_*`, `TensorProto` dtype enum, `numpy_helper`)
over a vendored protobuf subset (`onnx_subset.proto`) whose field numbering
matches the official schema byte-for-byte — models written here load in
stock onnx/onnxruntime and vice versa. Used automatically when the real
`onnx` package is absent (reference contrib/onnx requires the pip package;
this removes that dependency).
"""
from __future__ import annotations

import numpy as _np

from . import onnx_subset_pb2 as _P

ModelProto = _P.ModelProto
GraphProto = _P.GraphProto
NodeProto = _P.NodeProto
TensorProto = _P.TensorProto
AttributeProto = _P.AttributeProto
ValueInfoProto = _P.ValueInfoProto

_NP_TO_ONNX = {
    _np.dtype(_np.float32): TensorProto.FLOAT,
    _np.dtype(_np.float64): TensorProto.DOUBLE,
    _np.dtype(_np.float16): TensorProto.FLOAT16,
    _np.dtype(_np.int32): TensorProto.INT32,
    _np.dtype(_np.int64): TensorProto.INT64,
    _np.dtype(_np.int8): TensorProto.INT8,
    _np.dtype(_np.uint8): TensorProto.UINT8,
    _np.dtype(_np.bool_): TensorProto.BOOL,
}
_ONNX_TO_NP = {v: k for k, v in _NP_TO_ONNX.items()}


def load(path):
    m = ModelProto()
    with open(path, "rb") as f:
        m.ParseFromString(f.read())
    return m


def save(model, path):
    with open(path, "wb") as f:
        f.write(model.SerializeToString())


class numpy_helper:
    @staticmethod
    def to_array(t: "_P.TensorProto") -> _np.ndarray:
        dt = _ONNX_TO_NP.get(t.data_type, _np.dtype(_np.float32))
        shape = tuple(t.dims)
        if t.raw_data:
            return _np.frombuffer(t.raw_data, dtype=dt).reshape(shape).copy()
        if t.float_data:
            return _np.asarray(t.float_data, _np.float32).astype(dt).reshape(shape)
        if t.int64_data:
            return _np.asarray(t.int64_data, _np.int64).astype(dt).reshape(shape)
        if t.int32_data:
            return _np.asarray(t.int32_data, _np.int32).astype(dt).reshape(shape)
        if t.double_data:
            return _np.asarray(t.double_data, _np.float64).astype(dt).reshape(shape)
        return _np.zeros(shape, dt)

    @staticmethod
    def from_array(arr: _np.ndarray, name: str = "") -> "_P.TensorProto":
        t = TensorProto()
        t.name = name
        t.dims.extend(arr.shape)
        t.data_type = _NP_TO_ONNX.get(arr.dtype, TensorProto.FLOAT)
        t.raw_data = _np.ascontiguousarray(arr).tobytes()
        return t


class helper:
    @staticmethod
    def make_attribute(name, value):
        a = AttributeProto()
        a.name = name
        if isinstance(value, float):
            a.type = AttributeProto.FLOAT
            a.f = value
        elif isinstance(value, bool) or isinstance(value, int):
            a.type = AttributeProto.INT
            a.i = int(value)
        elif isinstance(value, str):
            a.type = AttributeProto.STRING
            a.s = value.encode()
        elif isinstance(value, bytes):
            a.type = AttributeProto.STRING
            a.s = value
        elif isinstance(value, _P.TensorProto):
            a.type = AttributeProto.TENSOR
            a.t.CopyFrom(value)
        elif isinstance(value, (list, tuple)):
            if value and isinstance(value[0], float):
                a.type = AttributeProto.FLOATS
                a.floats.extend(value)
            elif value and isinstance(value[0], str):
                a.type = AttributeProto.STRINGS
                a.strings.extend(v.encode() for v in value)
            else:
                a.type = AttributeProto.INTS
                a.ints.extend(int(v) for v in value)
        else:
            raise TypeError(f"unsupported attribute value {value!r}")
        return a

    @staticmethod
    def make_node(op_type, inputs, outputs, name=None, domain=None, **attrs):
        n = NodeProto()
        n.op_type = op_type
        n.input.extend(inputs)
        n.output.extend(outputs)
        if name:
            n.name = name
        if domain:
            n.domain = domain
        for k, v in sorted(attrs.items()):
            n.attribute.append(helper.make_attribute(k, v))
        return n

    @staticmethod
    def make_tensor(name, data_type, dims, vals, raw=False):
        t = TensorProto()
        t.name = name
        t.data_type = data_type
        t.dims.extend(dims)
        if raw:
            t.raw_data = vals
        elif data_type == TensorProto.FLOAT:
            t.float_data.extend(float(v) for v in vals)
        elif data_type == TensorProto.DOUBLE:
            t.double_data.extend(float(v) for v in vals)
        elif data_type in (TensorProto.INT64,):
            t.int64_data.extend(int(v) for v in vals)
        else:
            t.int32_data.extend(int(v) for v in vals)
        return t

    @staticmethod
    def make_tensor_value_info(name, elem_type, shape):
        vi = ValueInfoProto()
        vi.name = name
        vi.type.tensor_type.elem_type = elem_type
        if shape is not None:
            for d in shape:
                dim = vi.type.tensor_type.shape.dim.add()
                if d is None or (isinstance(d, str)):
                    dim.dim_param = str(d or "?")
                else:
                    dim.dim_value = int(d)
        return vi

    @staticmethod
    def make_graph(nodes, name, inputs, outputs, initializer=()):
        g = GraphProto()
        g.name = name
        g.node.extend(nodes)
        g.input.extend(inputs)
        g.output.extend(outputs)
        g.initializer.extend(initializer)
        return g

    @staticmethod
    def make_model(graph, producer_name="mxnet_tpu", opset=13):
        m = ModelProto()
        m.ir_version = 8
        m.producer_name = producer_name
        m.graph.CopyFrom(graph)
        op = m.opset_import.add()
        op.domain = ""
        op.version = opset
        return m


def attr_dict(node: "_P.NodeProto"):
    """Decode a NodeProto's attributes into a python dict."""
    out = {}
    for a in node.attribute:
        T = AttributeProto
        if a.type == T.FLOAT:
            out[a.name] = a.f
        elif a.type == T.INT:
            out[a.name] = a.i
        elif a.type == T.STRING:
            out[a.name] = a.s.decode()
        elif a.type == T.TENSOR:
            out[a.name] = numpy_helper.to_array(a.t)
        elif a.type == T.FLOATS:
            out[a.name] = list(a.floats)
        elif a.type == T.INTS:
            out[a.name] = list(a.ints)
        elif a.type == T.STRINGS:
            out[a.name] = [s.decode() for s in a.strings]
    return out
