"""Contrib namespace — AMP, quantization, ONNX-ish export, extras.

Mirrors the capability surface of reference python/mxnet/contrib/ (AMP,
quantization, tensorrt, onnx, text, …) with TPU-native mechanisms.
"""
from . import amp
from . import quantization
from . import text
from . import tensorboard
from . import onnx
from . import svrg_optimization
