"""INT8 quantization path (reference src/operator/quantization/ +
python/mxnet/contrib/quantization.py).

TPU-native mechanism: symmetric int8 quantization with f32 scales; quantized
matmul/conv run as int8×int8→int32 dots (the MXU's int8 mode) followed by a
rescale — the analog of the reference's quantized_conv/quantized_fully_connected
ops. Calibration mirrors the reference's minmax and KL-entropy modes
(quantization.py _calibrate_quantized_sym:142).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from ..ndarray import NDArray


def _raw(x):
    return x._data if isinstance(x, NDArray) else jnp.asarray(x)


# ---------------------------------------------------------------------------
# Core quantize/dequantize/requantize ops live in ops/quantized.py so they
# register at package import (reference registers at library load —
# quantize.cc:51, quantize_v2.cc:66). Re-exported here for compatibility.
# ---------------------------------------------------------------------------
from ..ops.quantized import (  # noqa: F401
    quantize, quantize_v2, dequantize, requantize)


# ---------------------------------------------------------------------------
# Quantized kernels: int8 × int8 → int32 on the MXU
# ---------------------------------------------------------------------------

def quantized_matmul(x_q, w_q, x_scale, w_scale):
    """int8 matmul with int32 accumulation, rescaled to f32. `w_scale`
    may be a scalar (per-tensor) or an (out_features,) vector
    (per-output-channel, the accuracy-preserving default — the reference's
    MKLDNN int8 path quantizes conv/FC weights channel-wise too)."""
    acc = lax.dot_general(
        x_q, w_q, (((x_q.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) / (x_scale * jnp.asarray(w_scale))


def quantized_conv2d(x_q, w_q, x_scale, w_scale, stride, padding):
    dn = lax.conv_dimension_numbers(x_q.shape, w_q.shape, ("NCHW", "OIHW", "NCHW"))
    acc = lax.conv_general_dilated(
        x_q.astype(jnp.int8), w_q.astype(jnp.int8), window_strides=stride,
        padding=padding, dimension_numbers=dn,
        preferred_element_type=jnp.int32)
    ws = jnp.asarray(w_scale)
    if ws.ndim == 1:  # per-output-channel -> broadcast over (N, O, H, W)
        ws = ws.reshape(1, -1, 1, 1)
    return acc.astype(jnp.float32) / (x_scale * ws)


# ---------------------------------------------------------------------------
# Calibration (reference quantization.py:142 _LayerOutputCollector /
# _LayerOutputMinMaxCollector + KL divergence _get_optimal_threshold:293)
# ---------------------------------------------------------------------------

class LayerOutputMinMaxCollector:
    def __init__(self):
        self.min_max: Dict[str, Tuple[float, float]] = {}

    def collect(self, name: str, arr):
        raw = _np.asarray(_raw(arr))
        lo, hi = float(raw.min()), float(raw.max())
        if name in self.min_max:
            plo, phi = self.min_max[name]
            lo, hi = min(lo, plo), max(hi, phi)
        self.min_max[name] = (lo, hi)


def _smooth_distribution(p, eps=0.0001):
    """Reference _smooth_distribution:272 — move eps mass from nonzero to
    zero bins so the KL ratio stays finite without 1e-12 clamps."""
    is_zeros = (p == 0).astype(_np.float64)
    n_zeros = int(is_zeros.sum())
    n_nonzeros = p.size - n_zeros
    if not n_nonzeros or not n_zeros:
        return p.astype(_np.float64)
    eps1 = eps * n_zeros / n_nonzeros
    return p.astype(_np.float64) + eps * is_zeros \
        - eps1 * (1.0 - is_zeros)


def _get_optimal_threshold(hist, hist_edges, num_quantized_bins=255,
                           max_clip_mass=0.0005):
    """KL-divergence calibration (reference _get_optimal_threshold:293).

    `max_clip_mass` bounds the activation mass a candidate threshold may
    clip (0.05%). Without it the raw KL metric can prefer thresholds that
    saturate 2-3% of a trained resnet's residual-stream activations —
    KL compares the folded histogram against its 255-bin requantization,
    and for sharply-peaked distributions the coarse-quantization penalty
    at wide thresholds dwarfs the small edge-bin mass the fold adds, so
    the minimum lands far inside the tail (measured: −4.3 accuracy points
    on resnet18; with the guard entropy matches minmax ±0.2 points —
    tests/test_int8_resnet_cifar.py)."""
    num_bins = len(hist)
    assert num_bins >= num_quantized_bins
    zero_bin = num_bins // 2
    total = float(hist.sum()) or 1.0
    thresholds = []
    divergences = []
    for i in range(num_quantized_bins // 2, zero_bin + 1, 2):
        p_start, p_stop = zero_bin - i, zero_bin + i
        outlier_mass = float(hist[:p_start].sum() + hist[p_stop:].sum())
        if outlier_mass / total > max_clip_mass:
            continue
        sliced = hist[p_start:p_stop].astype(_np.float64)
        p = sliced.copy()
        p[0] += hist[:p_start].sum()
        p[-1] += hist[p_stop:].sum()
        # quantize p into num_quantized_bins, then expand back
        factor = len(sliced) / num_quantized_bins
        q = _np.zeros_like(p)
        for j in range(num_quantized_bins):
            lo = int(j * factor)
            hi = int((j + 1) * factor) if j != num_quantized_bins - 1 else len(sliced)
            seg = sliced[lo:hi]
            nz = (seg != 0).sum()
            if nz:
                q[lo:hi] = _np.where(seg != 0, seg.sum() / nz, 0)
        p = _smooth_distribution(p)
        q = _smooth_distribution(q)
        p /= p.sum()
        q /= q.sum()
        kl = float(_np.sum(p * _np.log(p / q)))
        thresholds.append(float(hist_edges[p_stop]))
        divergences.append(kl)
    if not thresholds:  # every candidate clipped too much: use full range
        return float(hist_edges[-1])
    best = int(_np.argmin(divergences))
    return thresholds[best]


def calib_entropy(samples: _np.ndarray, num_bins=8001) -> Tuple[float, float]:
    samples = _np.asarray(samples).ravel()
    amax = float(_np.abs(samples).max()) or 1.0
    hist, edges = _np.histogram(samples, bins=num_bins, range=(-amax, amax))
    th = _get_optimal_threshold(hist, edges)
    return -th, th


# ---------------------------------------------------------------------------
# Model-level driver (reference quantize_model:429)
# ---------------------------------------------------------------------------

def _quantize_weight(weight, per_channel=False):
    """Symmetric int8 weight quantization -> (w_q int8, w_scale).
    per_channel=True returns an (out_channels,) scale vector computed over
    each output filter/row (axis 0 of OIHW / (out, in)) — per-tensor scales
    lose 3-4 accuracy points on a trained resnet18 (the wide dynamic-range
    spread across filters wastes most of the int8 grid on small filters)."""
    w = _np.asarray(_raw(weight), dtype=_np.float32)
    if per_channel and w.ndim >= 2:
        amax = _np.abs(w).reshape(w.shape[0], -1).max(axis=1)
        amax = _np.where(amax > 0, amax, 1.0)
        scale = (127.0 / amax).astype(_np.float32)
        w_q = jnp.asarray(
            _np.clip(_np.round(w * scale.reshape((-1,) + (1,) * (w.ndim - 1))),
                     -127, 127).astype(_np.int8))
        return w_q, jnp.asarray(scale)
    amax = float(_np.abs(w).max()) or 1.0
    scale = 127.0 / amax
    w_q = jnp.asarray(_np.clip(_np.round(w * scale), -127, 127)
                      .astype(_np.int8))
    return w_q, scale


class QuantizedDense:
    """Int8 inference wrapper for a Dense layer's weight."""

    def __init__(self, weight, bias=None, calib_range=None):
        self.w_q, self.w_scale = _quantize_weight(weight)
        self.w_amax = 127.0 / self.w_scale
        self.bias = _raw(bias) if bias is not None else None
        self.calib_range = calib_range

    def __call__(self, x):
        xr = _raw(x)
        if self.calib_range is not None:
            lo, hi = self.calib_range
            amax = max(abs(lo), abs(hi)) or 1.0
        else:
            amax = float(jnp.max(jnp.abs(xr)))
        x_scale = 127.0 / amax
        x_q = jnp.clip(jnp.round(xr * x_scale), -127, 127).astype(jnp.int8)
        out = quantized_matmul(x_q, self.w_q, x_scale, self.w_scale)
        if self.bias is not None:
            out = out + self.bias
        return NDArray(out) if isinstance(x, NDArray) else out


def quantize_model(sym=None, arg_params=None, aux_params=None, *,
                   quantized_dtype="int8", calib_mode="naive", calib_data=None,
                   num_calib_examples=None, excluded_sym_names=None, ctx=None,
                   logger=None):
    """Reference-shaped entry (quantization.py quantize_model:429): returns
    (sym, arg_params, aux_params) with weights pre-quantized to int8 plus
    per-tensor scales stored alongside (<name>_scale)."""
    if quantized_dtype not in ("int8", "uint8", "auto"):
        raise MXNetError("quantized_dtype must be int8/uint8/auto")
    excluded = set(excluded_sym_names or ())
    out_args = {}
    for k, v in (arg_params or {}).items():
        raw = _np.asarray(_raw(v))
        if k in excluded or not _np.issubdtype(raw.dtype, _np.floating) \
                or k.endswith(("_bias", "_beta", "_gamma")):
            out_args[k] = NDArray(jnp.asarray(raw))
            continue
        amax = float(_np.abs(raw).max()) or 1.0
        scale = 127.0 / amax
        q = _np.clip(_np.round(raw * scale), -127, 127).astype(_np.int8)
        out_args[k] = NDArray(jnp.asarray(q))
        out_args[k + "_scale"] = NDArray(jnp.float32(scale))
    return sym, out_args, dict(aux_params or {})


# ---------------------------------------------------------------------------
# End-to-end gluon INT8 inference (reference quantize_net:791 — graph
# rewrite to quantized ops + calibrated requantize ranges; here the
# rewrite swaps each Conv2D/Dense forward for an int8 MXU kernel)
# ---------------------------------------------------------------------------

def _iter_blocks(block, out):
    out.append(block)
    for child in block._children.values():
        _iter_blocks(child, out)
    return out


def quantize_net(net, calib_data=None, calib_mode="entropy",
                 num_calib_batches=None, exclude=(), logger=None):
    """Quantize a trained gluon net IN PLACE for int8 inference.

    Walks the block tree; every Conv2D (NCHW, groups=1, no dilation) and
    Dense layer gets its weight pre-quantized to int8 and its forward
    replaced by an int8xint8->int32 MXU kernel with a calibrated input
    scale. Calibration runs `calib_data` (iterable of input batches)
    through the fp32 net, collecting each target layer's input
    distribution: 'entropy' uses the reference KL-threshold search
    (calib_entropy), 'minmax' the observed range, 'naive' calibrates per
    batch at inference time. Returns the list of quantized layer names.
    """
    from ..gluon import nn as _nn

    # the int8 path is eager per layer: deactivate every HybridBlock and
    # drop any cached fp32 graphs — a hybridized parent would otherwise
    # replay its cached fp32 trace, skipping calibration hooks AND the
    # quantized forwards entirely
    for blk in _iter_blocks(net, []):
        if hasattr(blk, "_active"):
            blk._active = False
        if hasattr(blk, "clear_cache"):
            blk.clear_cache()  # also evicts the shared engine-cache entries
        elif hasattr(blk, "_cached_graphs"):
            blk._cached_graphs.clear()

    targets = []
    for blk in _iter_blocks(net, []):
        if blk.name in exclude or getattr(blk, "weight", None) is None:
            continue
        if isinstance(blk, _nn.Conv2D):
            kw = blk._kwargs
            if kw["num_group"] == 1 and tuple(kw["dilate"]) == (1, 1) \
                    and kw["layout"] == "NCHW":
                targets.append(blk)
        elif isinstance(blk, _nn.Dense):
            targets.append(blk)
    if not targets:
        return []

    if calib_mode not in ("entropy", "minmax", "naive"):
        raise MXNetError(
            f"unknown calib_mode {calib_mode!r}; use entropy/minmax/naive")
    ranges: Dict[int, Tuple[float, float]] = {}
    if calib_mode in ("entropy", "minmax"):
        if calib_data is None:
            raise MXNetError(
                f"calib_mode={calib_mode!r} needs calib_data batches")
        samples: Dict[int, List[_np.ndarray]] = {id(b): [] for b in targets}

        def _collector(blk):
            def hook(b, inputs):
                raw = _np.asarray(_raw(inputs[0]), _np.float32)
                # bounded reservoir per layer: enough for the histogram
                if sum(s.size for s in samples[id(blk)]) < 2_000_000:
                    samples[id(blk)].append(raw.ravel())
            return hook

        handles = [b.register_forward_pre_hook(_collector(b))
                   for b in targets]
        n = 0
        for batch in calib_data:
            net(batch)
            n += 1
            if num_calib_batches is not None and n >= num_calib_batches:
                break
        for h in handles:
            h.detach()
        uncalibrated = [b.name for b in targets if not samples[id(b)]]
        if uncalibrated:
            raise MXNetError(
                "calibration never reached layers "
                f"{uncalibrated[:5]} — they are not on the forward path of "
                "the calib_data batches (exclude them or fix calib_data)")
        for blk in targets:
            data = _np.concatenate(samples[id(blk)])
            if calib_mode == "entropy":
                ranges[id(blk)] = calib_entropy(data)
            else:
                ranges[id(blk)] = (float(data.min()), float(data.max()))

    quantized = []
    for blk in targets:
        w_q, w_scale = _quantize_weight(blk.weight.data(), per_channel=True)
        lohi = ranges.get(id(blk))
        a_amax = None
        if lohi is not None:
            a_amax = max(abs(lohi[0]), abs(lohi[1])) or 1.0
        act = blk._activation

        if isinstance(blk, _nn.Dense):
            flatten = blk._flatten

            def fwd(F, x, weight, bias=None, _wq=w_q, _ws=w_scale,
                    _am=a_amax, _act=act, _flat=flatten):
                xr = _raw(x)
                if _flat and xr.ndim > 2:
                    xr = xr.reshape(xr.shape[0], -1)
                am = _am if _am is not None else \
                    float(jnp.max(jnp.abs(xr))) or 1.0
                xs = 127.0 / am
                x_q = jnp.clip(jnp.round(xr * xs), -127, 127).astype(jnp.int8)
                out = quantized_matmul(x_q, _wq, xs, _ws)
                if bias is not None:
                    out = out + _raw(bias)
                res = NDArray(out)
                return F.Activation(res, act_type=_act) if _act else res
        else:
            kw = blk._kwargs
            stride = tuple(kw["stride"])
            pad = tuple(kw["pad"])
            padding = [(pad[0], pad[0]), (pad[1], pad[1])]

            def fwd(F, x, weight, bias=None, _wq=w_q, _ws=w_scale,
                    _am=a_amax, _act=act, _st=stride, _pd=padding):
                xr = _raw(x)
                am = _am if _am is not None else \
                    float(jnp.max(jnp.abs(xr))) or 1.0
                xs = 127.0 / am
                x_q = jnp.clip(jnp.round(xr * xs), -127, 127).astype(jnp.int8)
                out = quantized_conv2d(x_q, _wq, xs, _ws, _st, _pd)
                if bias is not None:
                    out = out + _raw(bias).reshape(1, -1, 1, 1)
                res = NDArray(out)
                return F.Activation(res, act_type=_act) if _act else res

        blk.hybrid_forward = fwd  # instance attr: forward passes F first
        quantized.append(blk.name)
    return quantized
