"""Dynamic loss scaler (reference python/mxnet/contrib/amp/loss_scaler.py).

Doubles the scale every `scale_window` overflow-free steps, halves it on
overflow and skips the update — identical policy to the reference; the
overflow check is a jitted all-finite reduction over the grad list (the
reference's multi_all_finite kernel, contrib/amp's LossScaler.has_overflow).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


class LossScaler:
    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0, scale_window=2000,
                 tolerance=0.0):
        self.loss_scale = float(init_scale)
        self._scale_factor = float(scale_factor)
        self._scale_window = int(scale_window)
        self._unskipped = 0

    @staticmethod
    @jax.jit
    def _all_finite(flats):
        ok = jnp.bool_(True)
        for f in flats:
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(f.astype(jnp.float32))))
        return ok

    def has_overflow(self, params_or_grads):
        """True if any grad is inf/nan. Accepts NDArrays or raw arrays."""
        flats = []
        for g in params_or_grads:
            raw = getattr(g, "_data", g)
            if raw is None:
                continue
            raw = getattr(raw, "_data", raw)
            if jnp.issubdtype(raw.dtype, jnp.floating):
                flats.append(raw.reshape(-1))
        if not flats:
            return False
        return not bool(self._all_finite(flats))

    def update_from_step(self, finite):
        """Designed sync point for the fused train step: reads the step's
        all-finite device scalar (blocking by necessity — the next step's
        loss scale is a host decision) and applies the reference policy.
        Lives here, off the trainer hot path, so mxlint's host-sync rule
        keeps the step functions themselves transfer-free."""
        return self.update_scale(not bool(finite))

    def state_dict(self):
        """Resumable state: the current scale and the overflow-free step
        count toward the next doubling. A resumed fp16 run that dropped
        these would restart at init_scale and skip/rescale differently
        from the uninterrupted trajectory."""
        return {"loss_scale": self.loss_scale, "unskipped": self._unskipped}

    def load_state_dict(self, d):
        self.loss_scale = float(d["loss_scale"])
        self._unskipped = int(d.get("unskipped", 0))

    def update_scale(self, overflow: bool):
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0
        return not overflow
