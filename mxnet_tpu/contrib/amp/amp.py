"""AMP front-door (reference python/mxnet/contrib/amp/amp.py).

API parity: init / init_trainer / scale_loss / unscale / convert_model /
convert_hybrid_block. Mechanism is TPU-native (see package docstring).
"""
from __future__ import annotations

import contextlib
from typing import List, Optional

import jax.numpy as jnp

from ...base import MXNetError
from ...ndarray import NDArray
from .loss_scaler import LossScaler

_state = {"on": False, "dtype": None}

# op families the reference forces to fp32 (contrib/amp/lists/symbol.py
# FP32_FUNCS) — normalization/softmax/losses; on TPU these already compute
# internally in f32 (ops/nn.py), so the lists are informational.
_FP32_OPS = ["BatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm", "L2Normalization",
             "softmax", "log_softmax", "SoftmaxOutput", "softmax_cross_entropy",
             "LinearRegressionOutput", "LogisticRegressionOutput", "MAERegressionOutput",
             "mean", "norm", "CTCLoss", "exp", "log", "erfinv"]
_LP16_OPS = ["Convolution", "Deconvolution", "FullyConnected", "RNN",
             "_contrib_interleaved_matmul_selfatt_qk",
             "_contrib_interleaved_matmul_selfatt_valatt",
             "_contrib_interleaved_matmul_encdec_qk",
             "_contrib_interleaved_matmul_encdec_valatt"]


def list_lp16_ops(target_dtype="bfloat16") -> List[str]:
    return list(_LP16_OPS)


def list_fp32_ops(target_dtype="bfloat16") -> List[str]:
    return list(_FP32_OPS)


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP globally (reference amp.init:104). After this, trainers
    built without an explicit dtype run their fused step in target_dtype."""
    dt = jnp.dtype(target_dtype)
    if dt not in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
        raise MXNetError("AMP target_dtype must be bfloat16 or float16")
    _state["on"] = True
    _state["dtype"] = str(dt)


def is_enabled() -> bool:
    return _state["on"]


def target_dtype() -> Optional[str]:
    return _state["dtype"] if _state["on"] else None


def init_trainer(trainer):
    """Attach a dynamic LossScaler to a gluon Trainer (amp.init_trainer:288).
    For bfloat16 the scaler stays at 1.0 (scaling is a no-op by design)."""
    if not _state["on"]:
        raise MXNetError("call amp.init() before amp.init_trainer()")
    scaler = LossScaler(init_scale=1.0 if _state["dtype"] == "bfloat16"
                        else 2.0 ** 16)
    trainer._amp_loss_scaler = scaler
    trainer._amp_original_scale = getattr(trainer, "_scale", 1.0)
    return trainer


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """with amp.scale_loss(loss, trainer) as l: l.backward()  (amp.py:214)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        yield loss
        return
    if hasattr(trainer, "_scale"):
        trainer._scale = trainer._amp_original_scale / scaler.loss_scale
    if isinstance(loss, (list, tuple)):
        yield type(loss)(l * scaler.loss_scale for l in loss)
    else:
        yield loss * scaler.loss_scale


def unscale(trainer):
    """Divide accumulated grads by the current loss scale (amp.unscale:550)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None or scaler.loss_scale == 1.0:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req != "null" and p._grad is not None:
            g = p._grad
            g._set_data(g._data * inv)


def amp_cast(x, dtype="bfloat16"):
    """Insert-cast op (reference amp_cast registered in src/operator/tensor/
    amp_cast.cc) — eager NDArray/raw cast that never upcasts fp32 params."""
    raw = x._data if isinstance(x, NDArray) else x
    out = raw.astype(jnp.dtype(dtype))
    return NDArray(out) if isinstance(x, NDArray) else out


def amp_multicast(*arrays, num_outputs=None):
    """Cast a list to their widest floating dtype (amp_multicast.cc)."""
    raws = [a._data if isinstance(a, NDArray) else a for a in arrays]
    wide = jnp.result_type(*[r.dtype for r in raws])
    outs = [r.astype(wide) for r in raws]
    return [NDArray(o) if isinstance(a, NDArray) else o
            for a, o in zip(arrays, outs)]


def convert_hybrid_block(block, target_dtype="bfloat16", cast_optional_params=False):
    """Cast a HybridBlock's parameters for low-precision inference
    (reference amp.convert_hybrid_block:602). Normalization params stay f32
    (their compute is f32 regardless; keeping them f32 preserves accuracy)."""
    dt = jnp.dtype(target_dtype)
    keep_f32 = ("gamma", "beta", "moving_mean", "moving_var",
                "running_mean", "running_var")
    for name, p in block.collect_params().items():
        if p._data is None:
            continue
        if any(name.endswith(k) for k in keep_f32):
            continue
        raw = p._data._data
        if jnp.issubdtype(raw.dtype, jnp.floating):
            p._data._set_data(raw.astype(dt))
            p.dtype = str(dt)
    return block


def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16",
                  target_dtype_ops=None, fp32_ops=None, conditional_fp32_ops=None,
                  excluded_sym_names=None, cast_optional_params=False):
    """Symbol-API variant (reference amp.convert_model:509): returns the same
    symbol plus params cast to target_dtype (XLA re-fuses casts at jit time,
    so no graph rewrite is needed — the cast IS the graph change)."""
    dt = jnp.dtype(target_dtype)
    excluded = set(excluded_sym_names or ())

    def _cast(d):
        out = {}
        for k, v in d.items():
            raw = v._data if isinstance(v, NDArray) else jnp.asarray(v)
            if k not in excluded and jnp.issubdtype(raw.dtype, jnp.floating):
                raw = raw.astype(dt)
            out[k] = NDArray(raw)
        return out
    return sym, _cast(arg_params), _cast(aux_params)
