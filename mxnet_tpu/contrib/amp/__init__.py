"""Automatic Mixed Precision (reference python/mxnet/contrib/amp/amp.py).

TPU-native AMP: the reference patches op namespaces to insert amp_cast nodes
(contrib/amp/amp.py convert_symbol:354); on TPU we instead run the fused
training step in bfloat16 with fp32 master weights (the MXU's native mode),
so `init()` just records the target dtype which trainers consult, and the
dynamic `LossScaler` is only engaged for float16 (bf16's fp32-sized exponent
makes scaling unnecessary — a capability uplift over GPU fp16 AMP).
"""
from .amp import (init, init_trainer, scale_loss, unscale, convert_hybrid_block,
                  convert_model, amp_cast, amp_multicast, is_enabled,
                  target_dtype, list_lp16_ops, list_fp32_ops)
from .loss_scaler import LossScaler
