"""SVRG optimization (reference python/mxnet/contrib/svrg_optimization/):
Stochastic Variance Reduced Gradient — maintains a snapshot of the weights
and the full-dataset gradient at that snapshot; each step uses
g_i(w) - g_i(w_snap) + g_full(w_snap).

TPU-native form: a functional SVRGState usable with any gluon net, plus an
SVRGModule mirroring the reference module API (fit refreshes the snapshot
every `update_freq` epochs).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..base import MXNetError
from .. import autograd
from ..ndarray import NDArray, zeros_like
from ..module.module import Module


class SVRGState:
    """Snapshot weights + full gradient at the snapshot."""

    def __init__(self, params: Dict[str, NDArray]):
        self._params = params
        self.snapshot: Dict[str, NDArray] = {}
        self.full_grad: Dict[str, NDArray] = {}

    def take_snapshot(self, data_iter, forward_loss, num_batches=None):
        """Record w_snap and mu = (1/N) sum_i grad_i(w_snap)."""
        self.snapshot = {k: NDArray(v._data) for k, v in self._params.items()}
        acc = {k: zeros_like(v) for k, v in self._params.items()}
        n = 0
        for batch in data_iter:
            if num_batches is not None and n >= num_batches:
                break
            with autograd.record():
                loss = forward_loss(batch)
            loss.backward()
            for k, v in self._params.items():
                g = v.grad() if callable(getattr(v, "grad", None)) else v._grad
                if g is not None:
                    acc[k]._set_data(acc[k]._data + g._data)
            n += 1
        if n == 0:
            raise MXNetError("take_snapshot: empty data iterator")
        self.full_grad = {k: NDArray(a._data / n) for k, a in acc.items()}
        return n

    def corrected_grad(self, key: str, grad_now: NDArray,
                       grad_at_snap: NDArray) -> NDArray:
        """g_i(w) - g_i(w_snap) + mu."""
        mu = self.full_grad[key]
        return NDArray(grad_now._data - grad_at_snap._data + mu._data)


class SVRGModule(Module):
    """Reference-shaped module (svrg_module.py SVRGModule): update applies
    variance-reduced gradients; fit refreshes the full-gradient snapshot
    every `update_freq` epochs."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, **kwargs)
        self.update_freq = int(update_freq)
        self._snapshot: Dict[str, NDArray] = {}
        self._mu: Dict[str, NDArray] = {}

    def update_full_grads(self, train_data):
        """Compute mu over the whole iterator at the current weights
        (reference SVRGModule.update_full_grads)."""
        self._snapshot = {k: NDArray(v._data)
                          for k, v in self._arg_params.items()}
        acc = {k: zeros_like(v) for k, v in self._arg_params.items()}
        train_data.reset()
        n = 0
        for batch in train_data:
            self.forward(batch, is_train=True)
            self.backward()
            for i, name, g in self._param_grads:
                if g is not None:
                    acc[name]._set_data(acc[name]._data + g._data)
            n += 1
        if n == 0:
            raise MXNetError("update_full_grads: empty data iterator")
        for k in acc:
            self._mu[k] = NDArray(acc[k]._data / n)
        train_data.reset()
        return n

    def update_svrg(self):
        """One variance-reduced update: re-evaluates the current batch's
        gradient at the snapshot weights, then applies
        g(w) - g(w_snap) + mu through the optimizer."""
        if not self._mu:
            raise MXNetError("call update_full_grads first")
        grads_now = {name: NDArray(g._data)
                     for _, name, g in self._param_grads if g is not None}
        # swap snapshot weights in, recompute grads on the same batch;
        # save the current-weight outputs so update_metric (which fit calls
        # AFTER update) still scores the real forward pass
        saved_outputs = self._exec.outputs
        current = {k: NDArray(v._data) for k, v in self._arg_params.items()}
        for k, v in self._arg_params.items():
            v._set_data(self._snapshot[k]._data)
        Module.forward(self, self._last_batch, is_train=True)
        self.backward()
        grads_snap = {name: NDArray(g._data)
                      for _, name, g in self._param_grads if g is not None}
        for k, v in self._arg_params.items():
            v._set_data(current[k]._data)
        self._exec.outputs = saved_outputs
        # install corrected grads and run the plain optimizer update
        for _, name, g in self._param_grads:
            if g is not None:
                g._set_data(grads_now[name]._data
                            - grads_snap[name]._data
                            + self._mu[name]._data)
        super().update()

    def forward(self, data_batch, is_train=None):
        self._last_batch = data_batch
        super().forward(data_batch, is_train=is_train)

    def update(self):
        if self._mu:
            self.update_svrg()
        else:
            super().update()

    def fit(self, train_data, *args, begin_epoch=0, num_epoch=None, **kwargs):
        """Epoch loop with periodic full-gradient refresh (reference
        svrg_module.py fit)."""
        assert num_epoch is not None, "please specify number of epochs"
        for epoch in range(begin_epoch, num_epoch):
            if epoch % self.update_freq == 0:
                if not self.binded:
                    # bind/init via one plain-fit epoch first, then snapshot
                    super().fit(train_data, *args, begin_epoch=epoch,
                                num_epoch=epoch + 1, **kwargs)
                    self.update_full_grads(train_data)
                    continue
                self.update_full_grads(train_data)
            super().fit(train_data, *args, begin_epoch=epoch,
                        num_epoch=epoch + 1, **kwargs)
