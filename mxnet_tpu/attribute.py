"""Attribute scoping (reference python/mxnet/attribute.py AttrScope):
attaches string attrs to every symbol created inside the scope —

    with mx.AttrScope(group="stage2"):
        fc = mx.sym.FullyConnected(...)
    fc.attr("group")  # "stage2"
"""
from __future__ import annotations

import threading

from .base import MXNetError

_state = threading.local()


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = [AttrScope()]
    return _state.stack


def current():
    return _stack()[-1]


class AttrScope:
    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise MXNetError("AttrScope values must be strings")
        self._attr = dict(kwargs)

    def get(self, attr=None):
        """Merge scope attrs with explicitly-passed attrs (explicit wins)."""
        if not self._attr:
            return dict(attr) if attr else {}
        out = dict(self._attr)
        if attr:
            out.update(attr)
        return out

    def __enter__(self):
        merged = AttrScope()
        merged._attr = {**current()._attr, **self._attr}
        _stack().append(merged)
        return self

    def __exit__(self, *exc):
        _stack().pop()
