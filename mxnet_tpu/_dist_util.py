"""Shared jax.distributed probes (no package-level imports — this must be
importable before anything touches the XLA backend)."""
from __future__ import annotations


def dist_client_active() -> bool:
    """Whether jax.distributed is already initialized, WITHOUT calling
    jax.process_count() (which would initialize the XLA backend and make a
    later jax.distributed.initialize impossible). Probes jax's private
    distributed state — the single place to update on a jax upgrade."""
    try:
        from jax._src import distributed as _dist
        return _dist.global_state.client is not None
    except Exception:
        return False
