"""mx.image namespace (reference python/mxnet/image/image.py + the C++
default augmenters in src/io/image_aug_default.cc).

Host-side image decode + augmentation. TPU-first split of labor: everything
here runs on the host CPU (decode, resize, crop, flip, color jitter, PCA
lighting) producing ready CHW float tensors; the chip only ever sees the
fused train step. cv2 is used when present, PIL as fallback, and raw
numpy for .npy/array payloads — nothing below requires the accelerator.
"""
from __future__ import annotations

import os
import random as _pyrandom

import numpy as _np

from .base import MXNetError
from .ndarray import NDArray, array


def _cv2():
    try:
        import cv2
        return cv2
    except ImportError:
        return None


def _pil():
    try:
        from PIL import Image
        return Image
    except ImportError:
        return None


def imread(filename, flag=1, to_rgb=True):
    """Read an image file to an HWC uint8 NDArray (reference image.py:imread)."""
    if filename.endswith(".npy"):
        return array(_np.load(filename))
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imdecode(buf, flag=1, to_rgb=True):
    """Decode an encoded image buffer (JPEG/PNG/...) to HWC uint8."""
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    elif isinstance(buf, _np.ndarray):
        buf = buf.tobytes()
    cv2 = _cv2()
    if cv2 is not None:
        img = cv2.imdecode(_np.frombuffer(buf, dtype=_np.uint8), flag)
        if img is None:
            raise MXNetError("cv2 cannot decode buffer")
        if to_rgb and img.ndim == 3:
            img = img[:, :, ::-1]
        return array(img.copy())
    Image = _pil()
    if Image is not None:
        import io as _io
        img = Image.open(_io.BytesIO(buf))
        img = img.convert("RGB" if flag else "L")
        a = _np.asarray(img)
        if not to_rgb and a.ndim == 3:
            a = a[:, :, ::-1]
        return array(_np.ascontiguousarray(a))
    raise MXNetError("imdecode requires cv2 or PIL")


def imresize(src, w, h, interp=1):
    """Resize to (h, w). Bilinear via cv2/PIL; nearest numpy fallback."""
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    cv2 = _cv2()
    if cv2 is not None:
        inter = {0: cv2.INTER_NEAREST, 1: cv2.INTER_LINEAR,
                 2: cv2.INTER_CUBIC, 3: cv2.INTER_AREA}.get(interp,
                                                            cv2.INTER_LINEAR)
        return array(cv2.resize(a, (w, h), interpolation=inter))
    Image = _pil()
    if Image is not None and a.dtype == _np.uint8:
        mode = Image.fromarray(a)
        rs = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC}
        return array(_np.asarray(mode.resize((w, h),
                                             rs.get(interp, Image.BILINEAR))))
    ri = (_np.arange(h) * a.shape[0] / h).astype(int).clip(0, a.shape[0] - 1)
    ci = (_np.arange(w) * a.shape[1] / w).astype(int).clip(0, a.shape[1] - 1)
    return array(a[ri][:, ci])


def resize_short(src, size, interp=1):
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    h, w = a.shape[:2]
    if h < w:
        nh, nw = size, int(w * size / h)
    else:
        nh, nw = int(h * size / w), size
    return imresize(a, nw, nh, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=1):
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    out = a[y0:y0 + h, x0:x0 + w]
    if size is not None:
        return imresize(out, size[0], size[1], interp)
    return array(out)


def center_crop(src, size, interp=1):
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    h, w = a.shape[:2]
    ow, oh = size
    x0 = (w - ow) // 2
    y0 = (h - oh) // 2
    return fixed_crop(a, x0, y0, ow, oh), (x0, y0, ow, oh)


def random_crop(src, size, interp=1):
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    h, w = a.shape[:2]
    ow, oh = size
    # python's random (not np.random): atomic under the GIL, safe for the
    # threaded decode pool
    x0 = _pyrandom.randint(0, max(w - ow, 0))
    y0 = _pyrandom.randint(0, max(h - oh, 0))
    return fixed_crop(a, x0, y0, ow, oh), (x0, y0, ow, oh)


def random_size_crop(src, size, area, ratio, interp=1):
    """Random area+aspect crop (reference image.py:random_size_crop — the
    Inception-style augmentation)."""
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    h, w = a.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _pyrandom.uniform(*area) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        ar = _np.exp(_pyrandom.uniform(*log_ratio))
        nw = int(round(_np.sqrt(target_area * ar)))
        nh = int(round(_np.sqrt(target_area / ar)))
        if nw <= w and nh <= h:
            x0 = _pyrandom.randint(0, w - nw)
            y0 = _pyrandom.randint(0, h - nh)
            return fixed_crop(a, x0, y0, nw, nh, size, interp), \
                (x0, y0, nw, nh)
    return center_crop(a, size, interp)


def color_normalize(src, mean, std=None):
    a = src.asnumpy().astype("float32") if isinstance(src, NDArray) else \
        _np.asarray(src, dtype="float32")
    a = a - _np.asarray(mean)
    if std is not None:
        a = a / _np.asarray(std)
    return array(a)


# ---------------------------------------------------------------------------
# Augmenters (reference python/mxnet/image/image.py Augmenter classes +
# src/io/image_aug_default.cc DefaultImageAugmenter). Each operates on an
# HWC float32 numpy array and returns one; pipelines compose left to right.
# ---------------------------------------------------------------------------

class Augmenter:
    """Image augmenter base (reference image.py:Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    """Resize shorter edge to `size`."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return _npx(resize_short(src, self.size, self.interp))


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return _npx(imresize(src, self.size[0], self.size[1], self.interp))


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return _npx(random_crop(src, self.size, self.interp)[0])


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return _npx(center_crop(src, self.size, self.interp)[0])


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = size, area, ratio, interp

    def __call__(self, src):
        return _npx(random_size_crop(src, self.size, self.area, self.ratio,
                                     self.interp)[0])


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return _npx(src)[:, ::-1]
        return _npx(src)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return _npx(src) * alpha


class ContrastJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], "float32")

    def __call__(self, src):
        src = _npx(src)
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        gray = (src * self._coef).sum()
        gray = 3.0 * (1.0 - alpha) / src.size * gray
        return src * alpha + gray

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast


class SaturationJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], "float32")

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        src = _npx(src)
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        gray = (src * self._coef).sum(axis=2, keepdims=True)
        return src * alpha + gray * (1.0 - alpha)


class HueJitterAug(Augmenter):
    """Hue rotation in YIQ space (reference image.py:HueJitterAug)."""
    _u = _np.array([[0.299, 0.587, 0.114],
                    [0.596, -0.274, -0.321],
                    [0.211, -0.523, 0.311]], "float32")

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        src = _npx(src)
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        u = _np.cos(alpha * _np.pi)
        w = _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]], "float32")
        t = _np.linalg.inv(self._u) @ bt @ self._u
        return _np.dot(src, t.T.astype("float32"))


class LightingAug(Augmenter):
    """PCA-based RGB noise (AlexNet lighting; reference image.py:LightingAug)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, "float32")
        self.eigvec = _np.asarray(eigvec, "float32")

    def __call__(self, src):
        alpha = _np.array([_pyrandom.gauss(0, self.alphastd)
                           for _ in range(3)], "float32")
        rgb = (self.eigvec * alpha) @ self.eigval
        return _npx(src) + rgb


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = _np.asarray(mean, "float32") if mean is not None else None
        self.std = _np.asarray(std, "float32") if std is not None else None

    def __call__(self, src):
        src = _npx(src)
        if self.mean is not None:
            src = src - self.mean
        if self.std is not None:
            src = src / self.std
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return _npx(src).astype(self.typ)


def _npx(x):
    """To float32 HWC numpy."""
    if isinstance(x, NDArray):
        x = x.asnumpy()
    return _np.asarray(x, dtype="float32")


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter list (reference image.py:CreateAugmenter;
    the flags mirror the C++ DefaultImageAugmenter parameters)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness:
        auglist.append(BrightnessJitterAug(brightness))
    if contrast:
        auglist.append(ContrastJitterAug(contrast))
    if saturation:
        auglist.append(SaturationJitterAug(saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Python-side image iterator over a .lst file or in-memory imglist
    (reference python/mxnet/image/image.py:ImageIter). Decodes + augments on
    the host; yields io.DataBatch of CHW float32."""

    def __init__(self, batch_size, data_shape, path_imglist=None,
                 path_root="", imglist=None, aug_list=None, shuffle=False,
                 seed=0, label_width=1, **kwargs):
        from .io.io import DataBatch  # noqa: F401 (type used in next())
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        items = []
        if path_imglist:
            with open(path_imglist) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) < 3:
                        continue
                    items.append(([float(x) for x in parts[1:-1]],
                                  os.path.join(path_root, parts[-1])))
        elif imglist:
            for lab, fname in imglist:
                lab = [float(lab)] if _np.isscalar(lab) else \
                    [float(x) for x in lab]
                items.append((lab, os.path.join(path_root, fname)))
        else:
            raise MXNetError("ImageIter needs path_imglist or imglist")
        self.items = items
        self.shuffle = shuffle
        self._rng = _np.random.RandomState(seed)
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(self.data_shape, **kwargs)
        self.reset()

    def reset(self):
        self._order = _np.arange(len(self.items))
        if self.shuffle:
            self._rng.shuffle(self._order)
        self._cur = 0

    @property
    def provide_data(self):
        return [("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shp = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [("softmax_label", shp)]

    def __iter__(self):
        return self

    def _load(self, fname):
        img = imread(fname).asnumpy().astype("float32")
        for aug in self.auglist:
            img = aug(img)
        img = _np.asarray(img, "float32")
        return _np.moveaxis(img, -1, 0)  # HWC -> CHW

    def next(self):
        from .io.io import DataBatch
        from .ndarray import array as nd_array
        if self._cur >= len(self.items):
            raise StopIteration
        xs, ys = [], []
        while len(xs) < self.batch_size and self._cur < len(self.items):
            lab, fname = self.items[self._order[self._cur]]
            self._cur += 1
            xs.append(self._load(fname))
            ys.append(lab[0] if self.label_width == 1 else
                      lab[:self.label_width])
        pad = self.batch_size - len(xs)
        if pad:
            xs += [xs[-1]] * pad
            ys += [ys[-1]] * pad
        return DataBatch(data=[nd_array(_np.stack(xs))],
                         label=[nd_array(_np.asarray(ys, "float32"))],
                         pad=pad)

    __next__ = next


class RandomOrderAug(Augmenter):
    """Apply a list of augmenters in random order (reference
    image.py:RandomOrderAug)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        order = list(range(len(self.ts)))
        _pyrandom.shuffle(order)
        for i in order:
            src = self.ts[i](src)
        return src


class ColorJitterAug(RandomOrderAug):
    """Brightness/contrast/saturation jitter in random order (reference
    image.py:ColorJitterAug)."""

    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class RandomGrayAug(Augmenter):
    """With probability p collapse to grayscale replicated over channels
    (reference image.py:RandomGrayAug — its 0.21/0.72/0.07 luma weights,
    not the Rec.601 ones the jitter augs use)."""
    _coef = _np.array([[[0.21, 0.72, 0.07]]], "float32")

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        src = _npx(src)
        if _pyrandom.random() < self.p:
            src = _np.repeat((src * self._coef).sum(axis=2, keepdims=True),
                             3, axis=2)
        return src


# ---------------------------------------------------------------------------
# Detection augmenters (reference python/mxnet/image/detection.py). Labels
# are (N, 5+) float arrays [cls, xmin, ymin, xmax, ymax, ...] with corners
# NORMALIZED to [0, 1]; every augmenter maps (HWC image, label) -> same.
# ---------------------------------------------------------------------------

class DetAugmenter:
    """Detection augmenter base (reference detection.py:41)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap a label-invariant classification augmenter (reference
    detection.py:67)."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, Augmenter):
            raise MXNetError("DetBorrowAug needs an image Augmenter")
        super().__init__()
        self.augmenter = augmenter

    def dumps(self):
        return [type(self).__name__, self.augmenter.dumps()]

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Apply ONE randomly chosen augmenter from the list, or none with
    probability skip_prob (reference detection.py:92)."""

    def __init__(self, aug_list, skip_prob=0):
        super().__init__(skip_prob=skip_prob)
        if not isinstance(aug_list, (list, tuple)):
            aug_list = [aug_list]
        for a in aug_list:
            if not isinstance(a, DetAugmenter):
                raise MXNetError("DetRandomSelectAug takes DetAugmenters")
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob if aug_list else 1.0

    def dumps(self):
        return [type(self).__name__, [a.dumps() for a in self.aug_list]]

    def __call__(self, src, label):
        if _pyrandom.random() < self.skip_prob:
            return src, label
        return _pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image and x-coordinates together (reference detection.py:128)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            src = _npx(src)[:, ::-1]
            label = label.copy()
            x1 = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - label[:, 1]
            label[:, 1] = x1
        return src, label


def _box_areas(boxes):
    return _np.maximum(0, boxes[:, 3] - boxes[:, 1]) * \
        _np.maximum(0, boxes[:, 2] - boxes[:, 0])


class DetRandomCropAug(DetAugmenter):
    """Constrained random crop (reference detection.py:154): the crop must
    cover >= min_object_covered of some box, sit inside the area/aspect
    ranges, and boxes keeping < min_eject_coverage of their area are
    dropped; after max_attempts the input passes through unchanged."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, area_range)
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts
        self.enabled = (0 < area_range[0] <= area_range[1]
                        and 0 < aspect_ratio_range[0] <= aspect_ratio_range[1])

    def __call__(self, src, label):
        src = _npx(src)
        h, w = src.shape[0], src.shape[1]
        prop = self._propose(label, h, w)
        if prop is not None:
            x, y, cw, ch, label = prop
            src = src[y:y + ch, x:x + cw]
        return src, label

    def _covered_enough(self, boxes, x1, y1, x2, y2):
        areas = _box_areas(boxes)
        good = areas > 0
        if not good.any():
            return False
        bx = boxes[good]
        ix1 = _np.maximum(bx[:, 0], x1)
        iy1 = _np.maximum(bx[:, 1], y1)
        ix2 = _np.minimum(bx[:, 2], x2)
        iy2 = _np.minimum(bx[:, 3], y2)
        inter = _np.maximum(0, ix2 - ix1) * _np.maximum(0, iy2 - iy1)
        cov = inter / areas[good]
        cov = cov[cov > 0]
        return cov.size > 0 and cov.min() > self.min_object_covered

    def _remap_labels(self, label, x, y, cw, ch, h, w):
        # crop box in normalized coords
        nx, ny, nw, nh = x / w, y / h, cw / w, ch / h
        out = label.copy()
        out[:, (1, 3)] = (out[:, (1, 3)] - nx) / nw
        out[:, (2, 4)] = (out[:, (2, 4)] - ny) / nh
        out[:, 1:5] = _np.clip(out[:, 1:5], 0, 1)
        keep_area = _box_areas(out[:, 1:5]) * nw * nh
        orig_area = _box_areas(label[:, 1:5])
        with _np.errstate(divide="ignore", invalid="ignore"):
            coverage = _np.where(orig_area > 0, keep_area / orig_area, 0.0)
        valid = (out[:, 3] > out[:, 1]) & (out[:, 4] > out[:, 2]) & \
            (coverage > self.min_eject_coverage)
        return out[valid] if valid.any() else None

    def _propose(self, label, h, w):
        if not self.enabled or h <= 0 or w <= 0:
            return None
        for _ in range(self.max_attempts):
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            area = _pyrandom.uniform(*self.area_range) * h * w
            ch = int(round((area / ratio) ** 0.5))
            cw = int(round(ch * ratio))
            if ch < 1 or cw < 1 or ch > h or cw > w:
                continue
            y = _pyrandom.randint(0, h - ch)
            x = _pyrandom.randint(0, w - cw)
            if not self._covered_enough(label[:, 1:5], x / w, y / h,
                                        (x + cw) / w, (y + ch) / h):
                continue
            new_label = self._remap_labels(label, x, y, cw, ch, h, w)
            if new_label is not None:
                return x, y, cw, ch, new_label
        return None


class DetRandomPadAug(DetAugmenter):
    """Random expansion padding (reference detection.py:325): embed the
    image in a larger canvas filled with pad_val and shrink the boxes
    accordingly."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33), area_range=(1.0, 3.0),
                 max_attempts=50, pad_val=(128, 128, 128)):
        if not isinstance(pad_val, (tuple, list)):
            pad_val = (pad_val,) * 3
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, area_range)
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.pad_val = pad_val
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.enabled = (area_range[1] > 1.0
                        and area_range[0] <= area_range[1]
                        and 0 < aspect_ratio_range[0] <= aspect_ratio_range[1])

    def __call__(self, src, label):
        src = _npx(src)
        h, w = src.shape[0], src.shape[1]
        prop = self._propose(h, w)
        if prop is not None:
            x, y, pw, ph = prop
            canvas = _np.empty((ph, pw, src.shape[2]), "float32")
            canvas[:] = _np.asarray(self.pad_val, "float32")
            canvas[y:y + h, x:x + w] = src
            src = canvas
            label = label.copy()
            label[:, (1, 3)] = (label[:, (1, 3)] * w + x) / pw
            label[:, (2, 4)] = (label[:, (2, 4)] * h + y) / ph
        return src, label

    def _propose(self, h, w):
        if not self.enabled or h <= 0 or w <= 0:
            return None
        for _ in range(self.max_attempts):
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            area = _pyrandom.uniform(*self.area_range) * h * w
            ph = int(round((area / ratio) ** 0.5))
            pw = int(round(ph * ratio))
            if ph - h < 2 or pw - w < 2:
                continue
            y = _pyrandom.randint(0, ph - h)
            x = _pyrandom.randint(0, pw - w)
            return x, y, pw, ph
        return None


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0):
    """One DetRandomSelectAug over per-constraint croppers (reference
    detection.py:419 — list-valued constraints make one cropper each)."""
    if isinstance(min_object_covered, (list, tuple)):
        n = len(min_object_covered)
    else:
        n = 1
        min_object_covered = [min_object_covered]
    aspect = aspect_ratio_range if isinstance(aspect_ratio_range[0],
                                              (list, tuple)) \
        else [aspect_ratio_range] * n
    areas = area_range if isinstance(area_range[0], (list, tuple)) \
        else [area_range] * n
    eject = min_eject_coverage if isinstance(min_eject_coverage,
                                             (list, tuple)) \
        else [min_eject_coverage] * n
    if not (len(aspect) == len(areas) == len(eject) == n):
        raise MXNetError(
            "CreateMultiRandCropAugmenter: list-valued constraints must "
            f"all have the same length (got {n}, {len(aspect)}, "
            f"{len(areas)}, {len(eject)})")
    crops = [DetRandomCropAug(min_object_covered=m, aspect_ratio_range=a,
                              area_range=r, min_eject_coverage=e,
                              max_attempts=max_attempts)
             for m, a, r, e in zip(min_object_covered, aspect, areas, eject)]
    return DetRandomSelectAug(crops, skip_prob=skip_prob)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 3.0),
                       min_eject_coverage=0.3, max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Standard detection pipeline (reference detection.py:484): optional
    resize -> constrained crop -> mirror -> expansion pad -> force resize
    -> cast -> color/pca/gray -> normalize."""
    augs = []
    if resize > 0:
        augs.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        augs.append(CreateMultiRandCropAugmenter(
            min_object_covered, aspect_ratio_range,
            (area_range[0], min(1.0, area_range[1])), min_eject_coverage,
            max_attempts, skip_prob=1 - rand_crop))
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5))
    if rand_pad > 0:
        augs.append(DetRandomSelectAug(
            [DetRandomPadAug(aspect_ratio_range, (1.0, area_range[1]),
                             max_attempts, pad_val)], 1 - rand_pad))
    augs.append(DetBorrowAug(ForceResizeAug((data_shape[2], data_shape[1]),
                                            inter_method)))
    augs.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        augs.append(DetBorrowAug(ColorJitterAug(brightness, contrast,
                                                saturation)))
    if hue:
        augs.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        augs.append(DetBorrowAug(LightingAug(
            pca_noise, _np.array([55.46, 4.794, 1.148]),
            _np.array([[-0.5675, 0.7192, 0.4009],
                       [-0.5808, -0.0045, -0.8140],
                       [-0.5836, -0.6948, 0.4203]]))))
    if rand_gray > 0:
        augs.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        augs.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return augs


class ImageDetIter(ImageIter):
    """Detection image iterator (reference detection.py:626): labels are
    the im2rec detection format [header_width, obj_width, extras...,
    (cls, xmin, ymin, xmax, ymax)*N] with normalized corners; batches pad
    the object axis with -1 rows to the estimated max object count."""

    def __init__(self, batch_size, data_shape, path_imglist=None,
                 path_root="", imglist=None, aug_list=None, shuffle=False,
                 seed=0, label_name="label", **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **kwargs)
        super().__init__(batch_size, data_shape, path_imglist=path_imglist,
                         path_root=path_root, imglist=imglist,
                         aug_list=[], shuffle=shuffle, seed=seed,
                         label_width=-1)
        self.det_auglist = aug_list
        self.label_name = label_name
        self.label_shape = self._estimate_label_shape()

    def _parse_label(self, raw):
        raw = _np.asarray(raw, "float32").ravel()
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if obj_width < 5:
            raise MXNetError(f"object width {obj_width} must be >= 5")
        body = raw[header_width:]
        if body.size % obj_width != 0:
            raise MXNetError("label length does not divide into objects")
        out = body.reshape(-1, obj_width)
        valid = _np.where(out[:, 0] > -0.5)[0]
        if valid.size < 1:
            raise MXNetError("no valid object in label")
        return out[valid]

    def _estimate_label_shape(self):
        max_count, width = 0, 5
        for lab, _ in self.items:
            parsed = self._parse_label(lab)
            max_count = max(max_count, parsed.shape[0])
            width = max(width, parsed.shape[1])
        return (max_count, width)

    @property
    def provide_label(self):
        return [(self.label_name, (self.batch_size,) + self.label_shape)]

    def reshape(self, data_shape=None, label_shape=None):
        if data_shape is not None:
            self.data_shape = tuple(data_shape)
        if label_shape is not None:
            self.check_label_shape(label_shape)
            self.label_shape = tuple(label_shape)

    def check_label_shape(self, label_shape):
        if len(label_shape) != 2 or label_shape[0] < self.label_shape[0] \
                or label_shape[1] < self.label_shape[1]:
            raise MXNetError(
                f"label_shape {label_shape} smaller than estimated "
                f"{self.label_shape}")

    def sync_label_shape(self, it, verbose=False):
        """Grow both iterators' label shapes to their union (reference
        detection.py:968 — train/val iterators must batch identically)."""
        if not isinstance(it, ImageDetIter):
            raise MXNetError("sync_label_shape needs an ImageDetIter")
        shape = (max(self.label_shape[0], it.label_shape[0]),
                 max(self.label_shape[1], it.label_shape[1]))
        self.label_shape = shape
        it.label_shape = shape
        return it

    def next(self):
        from .io.io import DataBatch
        from .ndarray import array as nd_array
        if self._cur >= len(self.items):
            raise StopIteration
        xs, ys = [], []
        n_obj, width = self.label_shape
        while len(xs) < self.batch_size and self._cur < len(self.items):
            lab, fname = self.items[self._order[self._cur]]
            self._cur += 1
            img = imread(fname).asnumpy().astype("float32")
            label = self._parse_label(lab)
            for aug in self.det_auglist:
                img, label = aug(img, label)
            xs.append(_np.moveaxis(_np.asarray(img, "float32"), -1, 0))
            padded = _np.full((n_obj, width), -1.0, "float32")
            k = min(n_obj, label.shape[0])
            padded[:k, :label.shape[1]] = label[:k]
            ys.append(padded)
        pad = self.batch_size - len(xs)
        if pad:
            xs += [xs[-1]] * pad
            ys += [ys[-1]] * pad
        return DataBatch(data=[nd_array(_np.stack(xs))],
                        label=[nd_array(_np.stack(ys))], pad=pad)
