"""mx.image namespace (reference python/mxnet/image/). Host-side image ops;
cv2 used when present, with numpy fallbacks for .npy/array inputs."""
from __future__ import annotations

import os

import numpy as _np

from .base import MXNetError
from .ndarray import NDArray, array


def imread(filename, flag=1, to_rgb=True):
    if filename.endswith(".npy"):
        return array(_np.load(filename))
    try:
        import cv2
    except ImportError:
        raise MXNetError("imread requires cv2 for encoded images; "
                         ".npy arrays are supported natively")
    img = cv2.imread(filename, flag)
    if img is None:
        raise MXNetError(f"cannot read {filename}")
    if to_rgb and img.ndim == 3:
        img = img[:, :, ::-1]
    return array(img.copy())


def imdecode(buf, flag=1, to_rgb=True):
    try:
        import cv2
    except ImportError:
        raise MXNetError("imdecode requires cv2")
    img = cv2.imdecode(_np.frombuffer(buf, dtype=_np.uint8), flag)
    if to_rgb and img is not None and img.ndim == 3:
        img = img[:, :, ::-1]
    return array(img.copy())


def imresize(src, w, h, interp=1):
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    ri = (_np.arange(h) * a.shape[0] / h).astype(int).clip(0, a.shape[0] - 1)
    ci = (_np.arange(w) * a.shape[1] / w).astype(int).clip(0, a.shape[1] - 1)
    return array(a[ri][:, ci])


def resize_short(src, size, interp=1):
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    h, w = a.shape[:2]
    if h < w:
        nh, nw = size, int(w * size / h)
    else:
        nh, nw = int(h * size / w), size
    return imresize(a, nw, nh, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=1):
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    out = a[y0:y0 + h, x0:x0 + w]
    if size is not None:
        return imresize(out, size[0], size[1], interp)
    return array(out)


def center_crop(src, size, interp=1):
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    h, w = a.shape[:2]
    ow, oh = size
    x0 = (w - ow) // 2
    y0 = (h - oh) // 2
    return fixed_crop(a, x0, y0, ow, oh), (x0, y0, ow, oh)


def random_crop(src, size, interp=1):
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    h, w = a.shape[:2]
    ow, oh = size
    x0 = _np.random.randint(0, max(w - ow, 0) + 1)
    y0 = _np.random.randint(0, max(h - oh, 0) + 1)
    return fixed_crop(a, x0, y0, ow, oh), (x0, y0, ow, oh)


def color_normalize(src, mean, std=None):
    a = src.asnumpy().astype("float32") if isinstance(src, NDArray) else \
        _np.asarray(src, dtype="float32")
    a = a - _np.asarray(mean)
    if std is not None:
        a = a / _np.asarray(std)
    return array(a)
