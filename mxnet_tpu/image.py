"""mx.image namespace (reference python/mxnet/image/image.py + the C++
default augmenters in src/io/image_aug_default.cc).

Host-side image decode + augmentation. TPU-first split of labor: everything
here runs on the host CPU (decode, resize, crop, flip, color jitter, PCA
lighting) producing ready CHW float tensors; the chip only ever sees the
fused train step. cv2 is used when present, PIL as fallback, and raw
numpy for .npy/array payloads — nothing below requires the accelerator.
"""
from __future__ import annotations

import os
import random as _pyrandom

import numpy as _np

from .base import MXNetError
from .ndarray import NDArray, array


def _cv2():
    try:
        import cv2
        return cv2
    except ImportError:
        return None


def _pil():
    try:
        from PIL import Image
        return Image
    except ImportError:
        return None


def imread(filename, flag=1, to_rgb=True):
    """Read an image file to an HWC uint8 NDArray (reference image.py:imread)."""
    if filename.endswith(".npy"):
        return array(_np.load(filename))
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imdecode(buf, flag=1, to_rgb=True):
    """Decode an encoded image buffer (JPEG/PNG/...) to HWC uint8."""
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    elif isinstance(buf, _np.ndarray):
        buf = buf.tobytes()
    cv2 = _cv2()
    if cv2 is not None:
        img = cv2.imdecode(_np.frombuffer(buf, dtype=_np.uint8), flag)
        if img is None:
            raise MXNetError("cv2 cannot decode buffer")
        if to_rgb and img.ndim == 3:
            img = img[:, :, ::-1]
        return array(img.copy())
    Image = _pil()
    if Image is not None:
        import io as _io
        img = Image.open(_io.BytesIO(buf))
        img = img.convert("RGB" if flag else "L")
        a = _np.asarray(img)
        if not to_rgb and a.ndim == 3:
            a = a[:, :, ::-1]
        return array(_np.ascontiguousarray(a))
    raise MXNetError("imdecode requires cv2 or PIL")


def imresize(src, w, h, interp=1):
    """Resize to (h, w). Bilinear via cv2/PIL; nearest numpy fallback."""
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    cv2 = _cv2()
    if cv2 is not None:
        inter = {0: cv2.INTER_NEAREST, 1: cv2.INTER_LINEAR,
                 2: cv2.INTER_CUBIC, 3: cv2.INTER_AREA}.get(interp,
                                                            cv2.INTER_LINEAR)
        return array(cv2.resize(a, (w, h), interpolation=inter))
    Image = _pil()
    if Image is not None and a.dtype == _np.uint8:
        mode = Image.fromarray(a)
        rs = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC}
        return array(_np.asarray(mode.resize((w, h),
                                             rs.get(interp, Image.BILINEAR))))
    ri = (_np.arange(h) * a.shape[0] / h).astype(int).clip(0, a.shape[0] - 1)
    ci = (_np.arange(w) * a.shape[1] / w).astype(int).clip(0, a.shape[1] - 1)
    return array(a[ri][:, ci])


def resize_short(src, size, interp=1):
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    h, w = a.shape[:2]
    if h < w:
        nh, nw = size, int(w * size / h)
    else:
        nh, nw = int(h * size / w), size
    return imresize(a, nw, nh, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=1):
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    out = a[y0:y0 + h, x0:x0 + w]
    if size is not None:
        return imresize(out, size[0], size[1], interp)
    return array(out)


def center_crop(src, size, interp=1):
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    h, w = a.shape[:2]
    ow, oh = size
    x0 = (w - ow) // 2
    y0 = (h - oh) // 2
    return fixed_crop(a, x0, y0, ow, oh), (x0, y0, ow, oh)


def random_crop(src, size, interp=1):
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    h, w = a.shape[:2]
    ow, oh = size
    # python's random (not np.random): atomic under the GIL, safe for the
    # threaded decode pool
    x0 = _pyrandom.randint(0, max(w - ow, 0))
    y0 = _pyrandom.randint(0, max(h - oh, 0))
    return fixed_crop(a, x0, y0, ow, oh), (x0, y0, ow, oh)


def random_size_crop(src, size, area, ratio, interp=1):
    """Random area+aspect crop (reference image.py:random_size_crop — the
    Inception-style augmentation)."""
    a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    h, w = a.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _pyrandom.uniform(*area) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        ar = _np.exp(_pyrandom.uniform(*log_ratio))
        nw = int(round(_np.sqrt(target_area * ar)))
        nh = int(round(_np.sqrt(target_area / ar)))
        if nw <= w and nh <= h:
            x0 = _pyrandom.randint(0, w - nw)
            y0 = _pyrandom.randint(0, h - nh)
            return fixed_crop(a, x0, y0, nw, nh, size, interp), \
                (x0, y0, nw, nh)
    return center_crop(a, size, interp)


def color_normalize(src, mean, std=None):
    a = src.asnumpy().astype("float32") if isinstance(src, NDArray) else \
        _np.asarray(src, dtype="float32")
    a = a - _np.asarray(mean)
    if std is not None:
        a = a / _np.asarray(std)
    return array(a)


# ---------------------------------------------------------------------------
# Augmenters (reference python/mxnet/image/image.py Augmenter classes +
# src/io/image_aug_default.cc DefaultImageAugmenter). Each operates on an
# HWC float32 numpy array and returns one; pipelines compose left to right.
# ---------------------------------------------------------------------------

class Augmenter:
    """Image augmenter base (reference image.py:Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    """Resize shorter edge to `size`."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return _npx(resize_short(src, self.size, self.interp))


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return _npx(imresize(src, self.size[0], self.size[1], self.interp))


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return _npx(random_crop(src, self.size, self.interp)[0])


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return _npx(center_crop(src, self.size, self.interp)[0])


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = size, area, ratio, interp

    def __call__(self, src):
        return _npx(random_size_crop(src, self.size, self.area, self.ratio,
                                     self.interp)[0])


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return _npx(src)[:, ::-1]
        return _npx(src)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return _npx(src) * alpha


class ContrastJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], "float32")

    def __call__(self, src):
        src = _npx(src)
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        gray = (src * self._coef).sum()
        gray = 3.0 * (1.0 - alpha) / src.size * gray
        return src * alpha + gray

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast


class SaturationJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], "float32")

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        src = _npx(src)
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        gray = (src * self._coef).sum(axis=2, keepdims=True)
        return src * alpha + gray * (1.0 - alpha)


class HueJitterAug(Augmenter):
    """Hue rotation in YIQ space (reference image.py:HueJitterAug)."""
    _u = _np.array([[0.299, 0.587, 0.114],
                    [0.596, -0.274, -0.321],
                    [0.211, -0.523, 0.311]], "float32")

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        src = _npx(src)
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        u = _np.cos(alpha * _np.pi)
        w = _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]], "float32")
        t = _np.linalg.inv(self._u) @ bt @ self._u
        return _np.dot(src, t.T.astype("float32"))


class LightingAug(Augmenter):
    """PCA-based RGB noise (AlexNet lighting; reference image.py:LightingAug)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, "float32")
        self.eigvec = _np.asarray(eigvec, "float32")

    def __call__(self, src):
        alpha = _np.array([_pyrandom.gauss(0, self.alphastd)
                           for _ in range(3)], "float32")
        rgb = (self.eigvec * alpha) @ self.eigval
        return _npx(src) + rgb


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = _np.asarray(mean, "float32") if mean is not None else None
        self.std = _np.asarray(std, "float32") if std is not None else None

    def __call__(self, src):
        src = _npx(src)
        if self.mean is not None:
            src = src - self.mean
        if self.std is not None:
            src = src / self.std
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return _npx(src).astype(self.typ)


def _npx(x):
    """To float32 HWC numpy."""
    if isinstance(x, NDArray):
        x = x.asnumpy()
    return _np.asarray(x, dtype="float32")


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter list (reference image.py:CreateAugmenter;
    the flags mirror the C++ DefaultImageAugmenter parameters)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness:
        auglist.append(BrightnessJitterAug(brightness))
    if contrast:
        auglist.append(ContrastJitterAug(contrast))
    if saturation:
        auglist.append(SaturationJitterAug(saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Python-side image iterator over a .lst file or in-memory imglist
    (reference python/mxnet/image/image.py:ImageIter). Decodes + augments on
    the host; yields io.DataBatch of CHW float32."""

    def __init__(self, batch_size, data_shape, path_imglist=None,
                 path_root="", imglist=None, aug_list=None, shuffle=False,
                 seed=0, label_width=1, **kwargs):
        from .io.io import DataBatch  # noqa: F401 (type used in next())
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        items = []
        if path_imglist:
            with open(path_imglist) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) < 3:
                        continue
                    items.append(([float(x) for x in parts[1:-1]],
                                  os.path.join(path_root, parts[-1])))
        elif imglist:
            for lab, fname in imglist:
                lab = [float(lab)] if _np.isscalar(lab) else \
                    [float(x) for x in lab]
                items.append((lab, os.path.join(path_root, fname)))
        else:
            raise MXNetError("ImageIter needs path_imglist or imglist")
        self.items = items
        self.shuffle = shuffle
        self._rng = _np.random.RandomState(seed)
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(self.data_shape, **kwargs)
        self.reset()

    def reset(self):
        self._order = _np.arange(len(self.items))
        if self.shuffle:
            self._rng.shuffle(self._order)
        self._cur = 0

    @property
    def provide_data(self):
        return [("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shp = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [("softmax_label", shp)]

    def __iter__(self):
        return self

    def _load(self, fname):
        img = imread(fname).asnumpy().astype("float32")
        for aug in self.auglist:
            img = aug(img)
        img = _np.asarray(img, "float32")
        return _np.moveaxis(img, -1, 0)  # HWC -> CHW

    def next(self):
        from .io.io import DataBatch
        from .ndarray import array as nd_array
        if self._cur >= len(self.items):
            raise StopIteration
        xs, ys = [], []
        while len(xs) < self.batch_size and self._cur < len(self.items):
            lab, fname = self.items[self._order[self._cur]]
            self._cur += 1
            xs.append(self._load(fname))
            ys.append(lab[0] if self.label_width == 1 else
                      lab[:self.label_width])
        pad = self.batch_size - len(xs)
        if pad:
            xs += [xs[-1]] * pad
            ys += [ys[-1]] * pad
        return DataBatch(data=[nd_array(_np.stack(xs))],
                         label=[nd_array(_np.asarray(ys, "float32"))],
                         pad=pad)

    __next__ = next
