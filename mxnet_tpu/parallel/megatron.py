"""Compute-partitioned (Megatron-style) tensor parallelism for the manual
pipeline programs (parallel/pipeline.py, ``tp_mode="partitioned"``).

The weight-sharded TP path gathers every sharded weight back to full size
once per step (tensor_parallel.gather_tp) — O(params/tp) wire volume and a
full-size weight copy per rank, which caps layer size at one chip's HBM.
This module keeps weights sharded FOREVER and moves the collectives onto
the (much smaller) activations, Megatron-LM style (arXiv:1909.08053):

  - column-parallel Dense (qkv / ffn-in): shard the OUT dim. No forward
    collective; the backward psums the input cotangent (``copy_to_tp``'s
    VJP is that psum).
  - row-parallel Dense (proj / ffn-out): shard the IN dim. The forward
    psums the partial products (``reduce_from_tp``); backward is local.
  - attention: heads split over 'tp' (head-blocks of the fused qkv
    projection land whole q/k/v triples per rank).
  - vocab-parallel embedding + cross-entropy: the (V, C) tables shard on
    vocab; the loss psums the per-rank max / log-normalizer / gold-logit
    pieces so the full-vocab logits tensor is NEVER materialized.
  - sequence parallelism (``sequence_parallel=True``): the regions TP
    cannot partition (layernorm / dropout / residual) run on (B, T/tp, C)
    sequence shards over the SAME tp axis group; the region boundaries
    become all_gather <-> psum_scatter pairs (``gather_from_sp`` /
    ``scatter_to_sp``) instead of pure psums, cutting the non-matmul
    activation memory by the tp factor.

Collectives and the replicated-gradient convention
--------------------------------------------------
All programs run inside ``zero.shard_map_compat`` (check_rep=False), where
a plain ``lax.psum`` transposes to ANOTHER psum — differentiating through
it would inflate gradients by tp (the exact failure pipeline.py's GPipe
loss masking documents). Every boundary collective here is therefore an
explicit ``jax.custom_vjp`` pair:

  ============== ==================== ====================
  op             forward              backward
  ============== ==================== ====================
  copy_to_tp     identity             psum
  reduce_from_tp psum                 identity
  gather_from_sp all_gather (tiled)   psum_scatter (tiled)
  scatter_to_sp  psum_scatter (tiled) all_gather (tiled)
  partial_grad   identity             cotangent / tp
  ============== ==================== ====================

Gradient convention for REPLICATED leaves (layernorm gamma/beta, position
tables, row-parallel biases, the bert MLM dense): the trainer psums their
per-rank gradients over tp, so every program must hand back PARTIAL sums.
Leaves consumed on per-token (sequence-sharded) or per-rank-slice compute
are naturally partial; leaves consumed by replicated compute produce
rank-identical FULL gradients and are wrapped with ``partial_grad`` (its
VJP divides by tp) so the psum reconstructs — not tp-multiplies — them.

Numerical parity: the programs call the registered op functions
(ops/nn.py ``fully_connected``/``layer_norm``/``dropout``/...) directly,
so with tp=1 the partitioned step is the same op sequence the gluon
oracle traces — the tp in {1, 2, 4} parity tests in
tests/test_partitioned_tp.py pin this. Each collective runs under a
``jax.named_scope`` region name (mx.tp.* / mx.sp.*) so span traces and
the roofline ledger attribute tp comm (tools/check_instrumentation.py
gates these).
"""
from __future__ import annotations

import functools
import math
import os
from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from ..ops import nn as _ops
from .mesh import axis_size as _axis_size

__all__ = [
    "copy_to_tp", "reduce_from_tp", "gather_from_sp", "scatter_to_sp",
    "partial_grad", "vocab_parallel_embedding",
    "vocab_parallel_cross_entropy", "PartitionConfig", "view_shape",
    "view_shard_dim", "CellPlan", "EmbedPlan", "HeadPlan", "plan_cell",
    "plan_embed", "plan_head", "cell_forward", "embed_forward",
    "head_loss_forward",
]


# ---------------------------------------------------------------------------
# Boundary collectives (explicit custom_vjp — see module docstring table)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tp(x, axis: str):
    """Megatron's f operator: identity forward, psum backward. Marks the
    entry of a column-parallel region — the cotangent flowing back out is
    the sum of every rank's partial contribution."""
    with jax.named_scope("mx.tp.copy_in"):
        return x


def _copy_fwd(x, axis):
    return copy_to_tp(x, axis), None


def _copy_bwd(axis, _res, ct):
    with jax.named_scope("mx.tp.grad_psum"):
        return (lax.psum(ct, axis),)


copy_to_tp.defvjp(_copy_fwd, _copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tp(x, axis: str):
    """Megatron's g operator: psum forward (row-parallel partial products
    -> full activation), identity backward (the downstream cotangent is
    already rank-identical)."""
    with jax.named_scope("mx.tp.act_psum"):
        return lax.psum(x, axis)


def _reduce_fwd(x, axis):
    return reduce_from_tp(x, axis), None


def _reduce_bwd(axis, _res, ct):
    return (ct,)


reduce_from_tp.defvjp(_reduce_fwd, _reduce_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_from_sp(x, axis: str, dim: int = 1):
    """Sequence-parallel region exit -> tensor-parallel region entry:
    all-gather the sequence shards (forward), psum_scatter the cotangent
    (backward) — each rank's partial cotangent for every token is summed
    and the owning rank keeps its slice."""
    with jax.named_scope("mx.sp.all_gather"):
        return lax.all_gather(x, axis, axis=dim, tiled=True)


def _gather_sp_fwd(x, axis, dim):
    return gather_from_sp(x, axis, dim), None


def _gather_sp_bwd(axis, dim, _res, ct):
    with jax.named_scope("mx.sp.grad_psum_scatter"):
        return (lax.psum_scatter(ct, axis, scatter_dimension=dim,
                                 tiled=True),)


gather_from_sp.defvjp(_gather_sp_fwd, _gather_sp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def scatter_to_sp(x, axis: str, dim: int = 1):
    """Tensor-parallel region exit -> sequence-parallel region entry:
    psum_scatter the partial products (forward — the psum of
    ``reduce_from_tp`` fused with the sequence split), all-gather the
    cotangent shards back (backward)."""
    with jax.named_scope("mx.sp.act_psum_scatter"):
        return lax.psum_scatter(x, axis, scatter_dimension=dim, tiled=True)


def _scatter_sp_fwd(x, axis, dim):
    return scatter_to_sp(x, axis, dim), None


def _scatter_sp_bwd(axis, dim, _res, ct):
    with jax.named_scope("mx.sp.grad_all_gather"):
        return (lax.all_gather(ct, axis, axis=dim, tiled=True),)


scatter_to_sp.defvjp(_scatter_sp_fwd, _scatter_sp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def partial_grad(x, axis: str):
    """Identity whose VJP divides by the tp degree. Wraps replicated
    leaves consumed by REPLICATED compute, converting their rank-identical
    full gradients to the partial-sum convention the trainer's tp psum
    expects (see module docstring)."""
    with jax.named_scope("mx.tp.partial_grad"):
        return x


def _partial_fwd(x, axis):
    return partial_grad(x, axis), None


def _partial_bwd(axis, _res, ct):
    n = _axis_size(axis)
    return (ct / n if jnp.issubdtype(ct.dtype, jnp.floating)
            else ct,)


partial_grad.defvjp(_partial_fwd, _partial_bwd)


# ---------------------------------------------------------------------------
# Partition configuration + weight-view layout helpers
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class PartitionConfig:
    """How the cell/embed/head programs partition: the tp mesh axis, its
    degree, and whether the non-matmul regions are sequence-sharded over
    the same axis group (Megatron sequence parallelism)."""
    axis: str
    n_tp: int
    sp: bool = False


def view_shape(shape: Tuple[int, ...], layout) -> Tuple[int, ...]:
    """Storage shape of a partitioned leaf. ``layout`` is None (replicated)
    or ``(dim, blocks)``: shard ``dim`` over tp in ``blocks`` interleaved
    blocks. blocks > 1 (the fused qkv's (3C, C): q/k/v row blocks) stores
    the leaf reshaped to (..., blocks, size/blocks, ...) and shards the
    WITHIN-block sub-dim, so rank r's slice is (q_r; k_r; v_r) — and the
    stored global shape is tp-degree independent (elastic resharding
    tp=2 -> tp=4 needs no permutation)."""
    if layout is None:
        return tuple(shape)
    dim, blocks = layout
    if blocks <= 1:
        return tuple(shape)
    return tuple(shape[:dim]) + (blocks, shape[dim] // blocks) \
        + tuple(shape[dim + 1:])


def view_shard_dim(layout) -> Optional[int]:
    """Which dim of the VIEW shape carries the tp sharding."""
    if layout is None:
        return None
    dim, blocks = layout
    return dim + 1 if blocks > 1 else dim


def _merge_view(w, layout):
    """Local view shard -> the flat local compute shape (inverse of the
    per-rank slice of ``view_shape``): (..., blocks, rows/tp, ...) ->
    (..., blocks*rows/tp, ...)."""
    if layout is None:
        return w
    dim, blocks = layout
    if blocks <= 1:
        return w
    shape = w.shape[:dim] + (w.shape[dim] * w.shape[dim + 1],) \
        + w.shape[dim + 2:]
    return w.reshape(shape)


# ---------------------------------------------------------------------------
# Vocab-parallel embedding + cross-entropy
# ---------------------------------------------------------------------------

def vocab_parallel_embedding(ids, table_local, axis: str):
    """PARTIAL embedding lookup on a vocab-sharded (V/tp, C) table: tokens
    outside this rank's vocab range contribute zeros. The caller reduces
    (``reduce_from_tp``) or reduce-scatters (``scatter_to_sp``) the
    partials — the full table is never gathered."""
    with jax.named_scope("mx.tp.vocab_embed"):
        v_local = table_local.shape[0]
        off = lax.axis_index(axis) * v_local
        loc = ids.astype(jnp.int32) - off
        ok = jnp.logical_and(loc >= 0, loc < v_local)
        emb = _ops.embedding(jnp.clip(loc, 0, v_local - 1), table_local)
        return jnp.where(ok[..., None], emb, jnp.zeros((), emb.dtype))


def vocab_parallel_cross_entropy(h, w_local, b_local, labels, axis: str):
    """Fused LM head + mean token cross-entropy over a vocab-sharded
    decoder, full-vocab logits never materialized. Per rank: local logits
    (B, T, V/tp) in f32; the global max (psum-free pmax, stop-gradient —
    a shift constant), the log-normalizer and the gold logit each cross
    ranks as (B, T) psums. Matches ``jnp.mean`` of
    gluon.loss.SoftmaxCrossEntropyLoss / recipes.moe.token_cross_entropy
    on the gathered logits to float tolerance."""
    logits = _ops.fully_connected(h, w_local, b_local,
                                  flatten=False).astype(jnp.float32)
    v_local = w_local.shape[0]
    off = lax.axis_index(axis) * v_local
    with jax.named_scope("mx.tp.vocab_pmax"):
        # stop_gradient INSIDE the pmax: pmax has no JVP rule, so the
        # linearization must see a constant (the shift is mathematically
        # gradient-free anyway)
        zmax = lax.pmax(lax.stop_gradient(jnp.max(logits, axis=-1)), axis)
    sumexp = jnp.sum(jnp.exp(logits - zmax[..., None]), axis=-1)
    norm = reduce_from_tp(sumexp, axis)                    # (B, T) psum
    loc = labels.astype(jnp.int32) - off
    ok = jnp.logical_and(loc >= 0, loc < v_local)
    gold_local = jnp.take_along_axis(
        logits, jnp.clip(loc, 0, v_local - 1)[..., None], axis=-1)[..., 0]
    gold = reduce_from_tp(jnp.where(ok, gold_local, 0.0), axis)
    return jnp.mean(zmax + jnp.log(norm) - gold)


# ---------------------------------------------------------------------------
# Layer plans: which plist slot plays which role, and each leaf's layout
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class _Dense:
    w: int
    b: Optional[int]


@dataclass(frozen=True)
class _MoE:
    gate_w: int
    w1: int
    w2: int
    top_k: int
    capacity_factor: float
    hidden: int
    n_experts: int


@dataclass(frozen=True)
class CellPlan:
    units: int
    heads: int
    head_major: bool
    use_blockwise: bool          # bert SelfAttention length-adaptive flash
    causal: bool                 # LC RingSelfAttention (causal LM cell)
    dense_oracle: bool           # LC dense_attention parity path
    attn_dropout: float
    ffn_dropout: float
    eps1: float
    eps2: float
    ln1: Tuple[int, int]
    ln2: Tuple[int, int]
    qkv: _Dense
    proj: _Dense
    ffn1: Optional[_Dense]
    ffn2: Optional[_Dense]
    moe: Optional[_MoE]
    layouts: Tuple[Optional[Tuple[int, int]], ...]


@dataclass(frozen=True)
class EmbedPlan:
    units: int
    word_w: int
    pos_w: int
    eps: float
    ln: Tuple[int, int]
    dropout: float
    layouts: Tuple[Optional[Tuple[int, int]], ...]


@dataclass(frozen=True)
class HeadPlan:
    units: int
    vocab: int
    eps: float
    ln: Tuple[int, int]
    mlm_dense: Optional[_Dense]      # bert MLM transform (dense + LN)
    mlm_ln: Optional[Tuple[int, int]]
    mlm_eps: float
    dec: _Dense
    layouts: Tuple[Optional[Tuple[int, int]], ...]


def _slot_map(plist):
    return {id(p): i for i, p in enumerate(plist)}


def _slot(slots, param, what):
    i = slots.get(id(param))
    if i is None:
        raise MXNetError(
            f"partitioned tp: {what} parameter is not in the stage's "
            "parameter list — pipeline stages must own their blocks")
    return i


def _require_divisible(value, n_tp, what):
    if value % n_tp != 0:
        raise MXNetError(
            f"partitioned tp: {what} ({value}) does not divide by "
            f"tp={n_tp}")


def _ln_plan(slots, ln, what):
    eps = float(getattr(ln, "_epsilon", 1e-5))
    return (_slot(slots, ln.gamma, f"{what}.gamma"),
            _slot(slots, ln.beta, f"{what}.beta")), eps


def _drop_rate(block) -> float:
    return float(block._rate) if block is not None else 0.0


def plan_cell(cell, plist, n_tp: int) -> CellPlan:
    """Build the partition plan for one transformer cell. Recognizes the
    bert ``TransformerEncoderCell`` / long-context ``_LCCell`` (dense FFN)
    and ``MoETransformerCell`` (gated-expert FFN) structures; anything
    else — or a non-fused qkv — raises with guidance."""
    from ..models.bert import SelfAttention
    slots = _slot_map(plist)
    attn = getattr(cell, "attn", None)
    ln1, ln2 = getattr(cell, "ln1", None), getattr(cell, "ln2", None)
    if attn is None or ln1 is None or ln2 is None:
        raise MXNetError(
            f"partitioned tp: cell {type(cell).__name__} is not a "
            "pre-LN transformer block (needs .ln1/.attn/.ln2 and an "
            ".ffn or .moe)")
    if getattr(attn, "qkv", None) is None:
        raise MXNetError(
            "partitioned tp requires the fused qkv projection "
            "(SelfAttention(fused_qkv=True)): separate q/k/v matmuls "
            "would shard into three tp-unfriendly K-splits")
    units = int(attn._units)
    heads = int(attn._heads)
    _require_divisible(heads, n_tp, "attention heads")
    is_bert_attn = isinstance(attn, SelfAttention)
    head_major = bool(getattr(attn, "_head_major", False))
    layouts: List[Optional[Tuple[int, int]]] = [None] * len(plist)

    qkv = _Dense(_slot(slots, attn.qkv.weight, "qkv.weight"),
                 _slot(slots, attn.qkv.bias, "qkv.bias"))
    # head-major fused qkv keeps whole (q,k,v,head) triples contiguous in
    # the out dim — a plain 1-block shard; the default (3, H, d) layout
    # shards inside each of the q/k/v row blocks (blocks=3)
    blocks = 1 if head_major else 3
    layouts[qkv.w] = (0, blocks)
    layouts[qkv.b] = (0, blocks)
    proj = _Dense(_slot(slots, attn.proj.weight, "proj.weight"),
                  _slot(slots, attn.proj.bias, "proj.bias"))
    layouts[proj.w] = (1, 1)

    (ln1_idx, eps1) = _ln_plan(slots, ln1, "ln1")
    (ln2_idx, eps2) = _ln_plan(slots, ln2, "ln2")

    ffn1 = ffn2 = moe = None
    ffn = getattr(cell, "ffn", None)
    moe_blk = getattr(cell, "moe", None)
    if ffn is not None:
        hidden = ffn.ffn1.weight.shape[0]
        _require_divisible(hidden, n_tp, "ffn hidden size")
        ffn1 = _Dense(_slot(slots, ffn.ffn1.weight, "ffn1.weight"),
                      _slot(slots, ffn.ffn1.bias, "ffn1.bias"))
        ffn2 = _Dense(_slot(slots, ffn.ffn2.weight, "ffn2.weight"),
                      _slot(slots, ffn.ffn2.bias, "ffn2.bias"))
        layouts[ffn1.w] = (0, 1)
        layouts[ffn1.b] = (0, 1)
        layouts[ffn2.w] = (1, 1)
        ffn_dropout = _drop_rate(getattr(ffn, "dropout", None))
    elif moe_blk is not None:
        if getattr(moe_blk, "_dense_ffn", False):
            raise MXNetError(
                "partitioned tp: the MoE dense_ffn oracle uses expert 0 "
                "only, which lives on one tp rank after expert sharding; "
                "run the oracle with tp_mode='sharded'")
        n_experts = int(moe_blk._num_experts)
        _require_divisible(n_experts, n_tp, "MoE experts")
        moe = _MoE(_slot(slots, moe_blk.gate_w, "moe.gate_w"),
                   _slot(slots, moe_blk.w1, "moe.w1"),
                   _slot(slots, moe_blk.w2, "moe.w2"),
                   int(moe_blk._top_k), float(moe_blk._capacity_factor),
                   int(moe_blk.w1.shape[2]), n_experts)
        layouts[moe.w1] = (0, 1)
        layouts[moe.w2] = (0, 1)
        ffn_dropout = 0.0
    else:
        raise MXNetError(
            f"partitioned tp: cell {type(cell).__name__} has neither "
            ".ffn (PositionwiseFFN) nor .moe (MoEPositionwiseFFN)")

    return CellPlan(
        units=units, heads=heads, head_major=head_major,
        use_blockwise=bool(getattr(attn, "_use_blockwise", False)),
        causal=not is_bert_attn,
        dense_oracle=bool(getattr(attn, "_dense", False)),
        attn_dropout=_drop_rate(getattr(attn, "dropout", None)),
        ffn_dropout=ffn_dropout, eps1=eps1, eps2=eps2,
        ln1=ln1_idx, ln2=ln2_idx, qkv=qkv, proj=proj,
        ffn1=ffn1, ffn2=ffn2, moe=moe, layouts=tuple(layouts))


def plan_embed(embed, plist, n_tp: int) -> EmbedPlan:
    """Partition plan for the embedding stage (word + position tables +
    LN + optional dropout — the bert/_LC/MoE embed-stage shape). Unused
    extra tables (bert's seg_embed) stay replicated with zero grads, like
    the oracle."""
    slots = _slot_map(plist)
    word = getattr(embed, "word_embed", None)
    pos = getattr(embed, "pos_embed", None)
    ln = getattr(embed, "embed_ln", None)
    if word is None or pos is None or ln is None:
        raise MXNetError(
            f"partitioned tp: embed stage {type(embed).__name__} needs "
            ".word_embed/.pos_embed/.embed_ln")
    vocab, units = word.weight.shape
    _require_divisible(vocab, n_tp, "vocab size")
    layouts: List[Optional[Tuple[int, int]]] = [None] * len(plist)
    word_w = _slot(slots, word.weight, "word_embed.weight")
    layouts[word_w] = (0, 1)
    ln_idx, eps = _ln_plan(slots, ln, "embed_ln")
    return EmbedPlan(
        units=int(units), word_w=word_w,
        pos_w=_slot(slots, pos.weight, "pos_embed.weight"),
        eps=eps, ln=ln_idx,
        dropout=_drop_rate(getattr(embed, "drop", None)),
        layouts=tuple(layouts))


def plan_head(head, plist, n_tp: int) -> HeadPlan:
    """Partition plan for the head stage: final LN (+ bert's MLM dense/LN
    transform) + vocab-sharded decoder fused into the cross-entropy."""
    slots = _slot_map(plist)
    ln = getattr(head, "ln", None)
    dec = getattr(head, "mlm_decoder", None) or getattr(head, "decoder",
                                                        None)
    if ln is None or dec is None:
        raise MXNetError(
            f"partitioned tp: head stage {type(head).__name__} needs "
            ".ln and .decoder/.mlm_decoder")
    vocab, units = dec.weight.shape
    _require_divisible(vocab, n_tp, "decoder vocab size")
    layouts: List[Optional[Tuple[int, int]]] = [None] * len(plist)
    dec_idx = _Dense(_slot(slots, dec.weight, "decoder.weight"),
                     _slot(slots, dec.bias, "decoder.bias"))
    layouts[dec_idx.w] = (0, 1)
    layouts[dec_idx.b] = (0, 1)
    ln_idx, eps = _ln_plan(slots, ln, "head.ln")
    mlm_dense = mlm_ln = None
    mlm_eps = 1e-5
    if getattr(head, "mlm_dense", None) is not None:
        mlm_dense = _Dense(
            _slot(slots, head.mlm_dense.weight, "mlm_dense.weight"),
            _slot(slots, head.mlm_dense.bias, "mlm_dense.bias"))
        mlm_ln, mlm_eps = _ln_plan(slots, head.mlm_ln, "mlm_ln")
    return HeadPlan(units=int(units), vocab=int(vocab), eps=eps, ln=ln_idx,
                    mlm_dense=mlm_dense, mlm_ln=mlm_ln, mlm_eps=mlm_eps,
                    dec=dec_idx, layouts=tuple(layouts))


# ---------------------------------------------------------------------------
# Program bodies (called from PipelineTrainer's schedule tick functions)
# ---------------------------------------------------------------------------

def _rep_fn(cfg: PartitionConfig, token_sharded: bool):
    """Leaf wrapper for replicated leaves: identity when their consuming
    compute is token-sharded (gradients are naturally partial), else
    ``partial_grad`` (rank-identical full grads -> partial convention)."""
    if token_sharded or cfg.n_tp <= 1:
        return lambda w: w
    return lambda w: partial_grad(w, cfg.axis)


def _dropout(x, key, rate, cfg: PartitionConfig, full_shape):
    """Dropout with SEQUENCE-PARITY masks: the bernoulli mask is always
    drawn at the full (unsharded) activation shape from the shared step
    key and sliced to the local tokens under sp, so the sp and non-sp
    programs drop the SAME elements for the same key (the sequence-
    parallel dropout parity test depends on it). Mirrors ops/nn.py
    ``dropout`` exactly when full_shape == x.shape."""
    if rate <= 0.0:
        return x
    keep = 1.0 - rate
    with jax.named_scope("mx.tp.dropout"):
        mask = jax.random.bernoulli(key, keep, tuple(full_shape))
        if mask.shape != x.shape:
            t_local = x.shape[1]
            mask = lax.dynamic_slice_in_dim(
                mask, lax.axis_index(cfg.axis) * t_local, t_local, axis=1)
        return jnp.where(mask, x / keep, jnp.zeros((), x.dtype))


def _enter_tp(x, cfg: PartitionConfig):
    """Non-matmul region -> matmul region boundary."""
    if cfg.n_tp <= 1:
        return x
    return gather_from_sp(x, cfg.axis, 1) if cfg.sp \
        else copy_to_tp(x, cfg.axis)


def _exit_tp(x, cfg: PartitionConfig):
    """Matmul region (partial products) -> non-matmul region boundary."""
    if cfg.n_tp <= 1:
        return x
    return scatter_to_sp(x, cfg.axis, 1) if cfg.sp \
        else reduce_from_tp(x, cfg.axis)


def _attention(plan: CellPlan, cfg: PartitionConfig, x, leaves, key,
               train: bool):
    """Head-partitioned self-attention on the gathered (B, T, C) input;
    returns the row-parallel proj's PARTIAL (B, T, C) product (the caller
    crosses the exit boundary and adds the replicated bias). Mirrors
    models/bert.SelfAttention / recipes/long_context.RingSelfAttention
    math exactly on the local head subset."""
    n_tp = cfg.n_tp
    h_local = plan.heads // n_tp
    d = plan.units // plan.heads
    wq = _merge_view(leaves[plan.qkv.w], plan.layouts[plan.qkv.w])
    bq = _merge_view(leaves[plan.qkv.b], plan.layouts[plan.qkv.b])
    qkv = _ops.fully_connected(x, wq, bq, flatten=False)  # (B, T, 3C/tp)
    B, T = qkv.shape[0], qkv.shape[1]
    if plan.head_major:
        qkv = qkv.reshape(B, T, h_local, 3, d)
        q, k, v = (jnp.transpose(qkv[:, :, :, i, :], (0, 2, 1, 3))
                   for i in range(3))
    else:
        qkv = qkv.reshape(B, T, 3, h_local, d)
        q, k, v = (jnp.transpose(qkv[:, :, i], (0, 2, 1, 3))
                   for i in range(3))
    if plan.causal:
        if plan.dense_oracle:
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                           preferred_element_type=jnp.float32) / (d ** 0.5)
            mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
            s = jnp.where(mask[None, None], s, -1e30)
            out = jnp.einsum("bhqk,bhkd->bhqd",
                             jax.nn.softmax(s, axis=-1),
                             v.astype(jnp.float32)).astype(q.dtype)
        else:
            from ..ops.attention import flash_attention_op
            out = flash_attention_op(q, k, v, causal=True)
    else:
        min_t = int(os.environ.get("MXNET_FLASH_ATTENTION_MIN_SEQ", 1024))
        if plan.use_blockwise and T >= min_t:
            from ..ops.attention import flash_attention_op
            out = flash_attention_op(q, k, v, causal=False)
        else:
            q2 = q.reshape(B * h_local, T, d)
            k2 = k.reshape(B * h_local, T, d)
            v2 = v.reshape(B * h_local, T, d)
            scores = jnp.matmul(q2, jnp.swapaxes(k2, -1, -2)) \
                / math.sqrt(d)
            att = _ops.softmax(scores, axis=-1)
            out = jnp.matmul(att, v2).reshape(B, h_local, T, d)
    out = jnp.transpose(out, (0, 2, 1, 3)).reshape(B, T, h_local * d)
    wp = leaves[plan.proj.w]                  # (C, C/tp): matching columns
    return _ops.fully_connected(out, wp, None, flatten=False)


def _tp_moe(plan: _MoE, cfg: PartitionConfig, flat, gate_w, w1_local,
            w2_local):
    """Expert-partitioned MoE FFN: gating is computed replicated over the
    FULL token set (identical dispatch/combine on every rank — same
    capacity/overflow semantics as the single-shard ``moe_ffn``), then
    each rank applies its E/tp expert slice of the dispatch/combine
    tensors. Gradients of gate_w / the input flow only through the local
    expert slices, so they are naturally partial. Returns the PARTIAL
    (N, C) combine product for the caller's exit collective."""
    from . import moe as _moe
    N = flat.shape[0]
    e_local = w1_local.shape[0]
    capacity = _moe.moe_capacity(N, plan.top_k, plan.capacity_factor,
                                 plan.n_experts)
    logits = flat @ gate_w
    dispatch, combine = _moe.topk_gating(logits, plan.top_k, capacity)
    denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9)       # normalize_gates
    r = lax.axis_index(cfg.axis)
    disp_l = lax.dynamic_slice_in_dim(dispatch, r * e_local, e_local,
                                      axis=1)
    comb_l = lax.dynamic_slice_in_dim(combine, r * e_local, e_local,
                                      axis=1)
    expert_in = jnp.einsum("nd,nec->ecd", flat, disp_l)
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", expert_in, w1_local))
    expert_out = jnp.einsum("ech,ehd->ecd", h, w2_local)
    return jnp.einsum("ecd,nec->nd", expert_out, comb_l)


def cell_forward(plan: CellPlan, cfg: PartitionConfig, leaves, h, key,
                 train: bool = True):
    """One partitioned transformer cell. ``h`` is (B, T, C) replicated, or
    (B, T/tp, C) under sequence parallelism; ``leaves`` are this rank's
    local view shards in plist order; ``key`` a typed PRNG key unique per
    (step, stage, layer, microbatch)."""
    rep = _rep_fn(cfg, cfg.sp)
    full_T = h.shape[1] * (cfg.n_tp if (cfg.sp and cfg.n_tp > 1) else 1)
    full_act = (h.shape[0], full_T, h.shape[2])

    a = _ops.layer_norm(h, rep(leaves[plan.ln1[0]]),
                        rep(leaves[plan.ln1[1]]), eps=plan.eps1)
    att = _attention(plan, cfg, _enter_tp(a, cfg), leaves,
                     jax.random.fold_in(key, 0), train)
    att = _exit_tp(att, cfg)
    att = att + rep(leaves[plan.proj.b])
    if train and plan.attn_dropout:
        att = _dropout(att, jax.random.fold_in(key, 1), plan.attn_dropout,
                       cfg, full_act)
    h = h + att

    b = _ops.layer_norm(h, rep(leaves[plan.ln2[0]]),
                        rep(leaves[plan.ln2[1]]), eps=plan.eps2)
    bf = _enter_tp(b, cfg)
    if plan.moe is not None:
        B, T, C = bf.shape
        y = _tp_moe(plan.moe, cfg, bf.reshape(B * T, C),
                    leaves[plan.moe.gate_w], leaves[plan.moe.w1],
                    leaves[plan.moe.w2]).reshape(B, T, C)
        y = _exit_tp(y, cfg)
    else:
        w1 = leaves[plan.ffn1.w]
        hdn = _ops.activation(
            _ops.fully_connected(bf, w1, leaves[plan.ffn1.b],
                                 flatten=False), act_type="gelu")
        y = _ops.fully_connected(hdn, leaves[plan.ffn2.w], None,
                                 flatten=False)
        y = _exit_tp(y, cfg)
        y = y + rep(leaves[plan.ffn2.b])
        if train and plan.ffn_dropout:
            y = _dropout(y, jax.random.fold_in(key, 2), plan.ffn_dropout,
                         cfg, full_act)
    return h + y


def embed_forward(plan: EmbedPlan, cfg: PartitionConfig, leaves, tokens,
                  key, train: bool = True):
    """Vocab-parallel embedding stage: partial word lookup -> reduce (or
    reduce-scatter to sequence shards) -> positions -> LN -> dropout.
    tokens: (B, T) int — the FULL sequence on every rank."""
    rep = _rep_fn(cfg, cfg.sp)
    T = tokens.shape[1]
    emb = vocab_parallel_embedding(tokens, leaves[plan.word_w], cfg.axis) \
        if cfg.n_tp > 1 else _ops.embedding(tokens, leaves[plan.word_w])
    pos_w = leaves[plan.pos_w]
    if cfg.sp and cfg.n_tp > 1:
        x = scatter_to_sp(emb, cfg.axis, 1)              # (B, T/tp, C)
        t_local = T // cfg.n_tp
        pos = lax.axis_index(cfg.axis) * t_local \
            + jnp.arange(t_local, dtype=jnp.int32)
        # per-rank position rows: grads land partial with no wrap
        x = x + _ops.embedding(pos, pos_w)[None]
    else:
        x = reduce_from_tp(emb, cfg.axis) if cfg.n_tp > 1 else emb
        pos = jnp.arange(T, dtype=jnp.int32)
        x = x + _ops.embedding(pos, rep(pos_w))[None]
    x = _ops.layer_norm(x, rep(leaves[plan.ln[0]]), rep(leaves[plan.ln[1]]),
                        eps=plan.eps)
    if train and plan.dropout:
        full = (x.shape[0], T, x.shape[2])
        x = _dropout(x, jax.random.fold_in(key, 0), plan.dropout, cfg,
                     full)
    return x


def head_loss_forward(plan: HeadPlan, cfg: PartitionConfig, leaves, h,
                      labels, key=None, train: bool = True):
    """Head stage fused with the vocab-parallel cross-entropy: LN (+ the
    bert MLM transform) on the (optionally sequence-sharded) activations,
    gather to full tokens, then the never-materialize-the-logits loss.
    labels: (B, T) int. Returns the scalar mean token loss (identical on
    every tp rank)."""
    rep = _rep_fn(cfg, cfg.sp)
    x = _ops.layer_norm(h, rep(leaves[plan.ln[0]]), rep(leaves[plan.ln[1]]),
                        eps=plan.eps)
    if plan.mlm_dense is not None:
        x = _ops.activation(
            _ops.fully_connected(x, rep(leaves[plan.mlm_dense.w]),
                                 rep(leaves[plan.mlm_dense.b]),
                                 flatten=False), act_type="gelu")
        x = _ops.layer_norm(x, rep(leaves[plan.mlm_ln[0]]),
                            rep(leaves[plan.mlm_ln[1]]), eps=plan.mlm_eps)
    if cfg.n_tp > 1:
        # region entry: the CE backprops only this rank's vocab slice into
        # x, so the boundary collective (psum / psum_scatter in the VJP)
        # completes x's cotangent before the replicated/per-token compute
        # above it
        x = gather_from_sp(x, cfg.axis, 1) if cfg.sp \
            else copy_to_tp(x, cfg.axis)
        return vocab_parallel_cross_entropy(
            x, leaves[plan.dec.w], leaves[plan.dec.b], labels, cfg.axis)
    logits = _ops.fully_connected(x, leaves[plan.dec.w],
                                  leaves[plan.dec.b],
                                  flatten=False).astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(
        logits, labels.astype(jnp.int32)[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold)
