"""Backward-overlapped gradient collectives: chunked-vjp segment planning.

The fused data-parallel step runs the whole backward, then reduces every
gradient bucket at the tail — all collective time is exposed. The standard
production-trainer fix is to chunk the backward and issue each fusion
bucket's collective as soon as the last gradient contributing to it
finalizes, so the scheduler can hoist the DMA under the remaining backward
dots. This module holds the pieces `DataParallelTrainer(overlap_grads=True)`
composes with `parallel/zero.py`:

  - a **chain extractor**: a linear list of child blocks whose sequential
    application equals the net's forward (pipeline_split() stages, a
    HybridSequential's children, or the model-zoo features+output shape —
    the same recipes the roofline bench walks);
  - a **segment planner**: the chain grouped into K segments of ~equal
    trainable-parameter bytes; each segment becomes one `jax.vjp` call in
    the step, and the segment's first parameter slots become the
    ``boundaries=`` hint to ``zero.plan_buckets`` so no bucket spans a
    segment;
  - the **per-bucket all-reduce** used when zero_update is off (native
    psum, or a compressed-wire reduce-scatter + all-gather composition),
    plus its wire-byte estimator for telemetry;
  - the ``@_segment_vjp_kernel`` donation decorator for eager segment-grad
    accumulation (mxlint's donation-safety pass knows it: reading a donated
    accumulator after the call is flagged).

The big win is on-chip (async collectives + the latency-hiding scheduler,
engine/xla_flags.py); the CPU host still verifies the *structure* — K
interleaved per-bucket collectives in the optimized HLO instead of one
fused tail block — and exact trajectory parity.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
from jax import lax
import jax.numpy as jnp

from ..base import MXNetError, env
from .. import engine as _engine
from . import zero as _zero

__all__ = ["Segment", "SegmentPlan", "chain_blocks", "plan_segments",
           "allreduce_bucket", "allreduce_wire_bytes",
           "accumulate_segment_grads"]

env.declare("MXNET_TPU_OVERLAP_GRADS", False, bool,
            "Default DataParallelTrainer(overlap_grads=...) to the "
            "backward-overlapped collective schedule (chunked-vjp backward, "
            "per-bucket collectives issued as segments finalize). Nets "
            "without a linear block chain fall back to the plain step with "
            "a warning when enabled this way.")
env.declare("MXNET_TPU_OVERLAP_SEGMENTS", 4, int,
            "Target number of backward vjp segments for the overlapped "
            "step (clamped to the net's chain length; >= 2 required)")


@dataclass(frozen=True)
class Segment:
    """One chunk of the backward: a run of chain blocks applied in order.

    ``uses`` are the plist slots the segment's forward consumes (first-use
    order — the vjp differentiates w.r.t. exactly these). ``owned`` are the
    slots whose gradient FINALIZES when this segment's pullback runs: a
    parameter shared across segments is owned by its earliest user, since
    the backward visits segments in reverse and the earliest user
    contributes last."""
    index: int
    names: Tuple[str, ...]
    blocks: Tuple[Any, ...] = field(compare=False)
    block_uses: Tuple[Tuple[int, ...], ...]
    uses: Tuple[int, ...]
    owned: Tuple[int, ...]


class SegmentPlan:
    """Segments plus the bucket-alignment view the trainer needs."""

    def __init__(self, segments: Sequence[Segment]):
        self.segments: Tuple[Segment, ...] = tuple(segments)
        self.segment_of_slot: Dict[int, int] = {
            i: s.index for s in self.segments for i in s.owned}
        # plan_buckets boundary hint: cut before each segment's first owned
        # slot. Owned slots are contiguous runs in declaration order
        # (plan_segments enforces it), so interval cuts align exactly.
        self.boundaries: Tuple[int, ...] = tuple(
            min(s.owned) for s in self.segments[1:] if s.owned)

    def __len__(self):
        return len(self.segments)

    @property
    def fingerprint(self):
        """Deterministic token for engine.config_fingerprint: two nets that
        segment differently must compile (and roofline-ledger) apart."""
        return tuple((s.index, s.names, s.uses, s.owned)
                     for s in self.segments)


def chain_blocks(net) -> Optional[List[Tuple[str, Any]]]:
    """A linear ``[(name, block), ...]`` chain whose sequential application
    reproduces ``net``'s forward, or None when the net has no such shape.

    Recognized shapes (the same recipes bench.py's roofline scenario walks):
    a ``pipeline_split()`` model (embed + cells + head), a HybridSequential,
    and the model-zoo ``features`` (HybridSequential) + ``output`` pair."""
    from ..gluon import nn as _nn
    from ..gluon.block import HybridBlock
    split = getattr(net, "pipeline_split", None)
    if callable(split):
        embed, cells, head = split()
        return ([("embed", embed)]
                + [(f"cell{i}", c) for i, c in enumerate(cells)]
                + [("head", head)])
    if isinstance(net, _nn.HybridSequential):
        return [(f"[{i}]", b) for i, b in enumerate(net._children.values())]
    feats = getattr(net, "features", None)
    out = getattr(net, "output", None)
    if isinstance(feats, _nn.HybridSequential) and isinstance(out, HybridBlock):
        return ([(f"features[{i}]", b)
                 for i, b in enumerate(feats._children.values())]
                + [("output", out)])
    return None


def plan_segments(net, plist: Sequence[Any], n_segments: int) -> SegmentPlan:
    """Group ``net``'s block chain into ``n_segments`` backward segments of
    ~equal owned-parameter bytes. Raises MXNetError when the net has no
    linear chain, when the chain covers parameters `plist` doesn't (or vice
    versa), or when segment ownership is not contiguous in declaration
    order (bucket boundaries are slot intervals)."""
    chain = chain_blocks(net)
    if not chain:
        raise MXNetError(
            f"net {type(net).__name__} has no linear block chain "
            "(pipeline_split() / HybridSequential / features+output); "
            "overlap_grads needs one to segment the backward")
    slot_of = {id(p): i for i, p in enumerate(plist)}
    per_block_uses: List[Tuple[int, ...]] = []
    for name, blk in chain:
        uses = []
        for p in blk.collect_params().values():
            i = slot_of.get(id(p))
            if i is None:
                raise MXNetError(
                    f"chain block {name} holds parameter {p.name!r} that "
                    "the trainer's parameter list doesn't (initialize the "
                    "net before constructing the trainer)")
            if i not in uses:
                uses.append(i)
        per_block_uses.append(tuple(uses))
    covered = {i for uses in per_block_uses for i in uses}
    missing = [i for i in range(len(plist)) if i not in covered]
    if missing:
        raise MXNetError(
            "net parameters outside the block chain (slots "
            f"{missing[:4]}…): their gradients would never finalize in a "
            "segmented backward; overlap_grads requires the chain to cover "
            "every parameter")
    # owner = earliest chain block using the slot (shared parameters get
    # their last backward contribution there)
    owner_block = {}
    for j, uses in enumerate(per_block_uses):
        for i in uses:
            owner_block.setdefault(i, j)

    k = max(2, int(n_segments))
    k = min(k, len(chain))
    sizes = [sum(int(getattr(plist[i]._data, "size", 0))
                 * jnp.dtype(plist[i].dtype or "float32").itemsize
                 for i in uses if owner_block[i] == j)
             for j, uses in enumerate(per_block_uses)]
    total = sum(sizes) or 1
    # cut at cumulative-bytes thresholds i*total/k: groups of ~equal owned
    # bytes; a block heavier than total/k simply swallows later thresholds
    # (fewer, fatter segments — never an infeasible plan)
    groups: List[List[int]] = []
    cur: List[int] = []
    acc = 0
    for j in range(len(chain)):
        cur.append(j)
        acc += sizes[j]
        if len(groups) < k - 1 and acc >= total * (len(groups) + 1) / k:
            groups.append(cur)
            cur = []
    if cur:
        groups.append(cur)

    segments = []
    for s, grp in enumerate(groups):
        uses: List[int] = []
        for j in grp:
            for i in per_block_uses[j]:
                if i not in uses:
                    uses.append(i)
        owned = tuple(sorted(i for i in uses
                             if owner_block[i] in grp))
        segments.append(Segment(
            index=s,
            names=tuple(chain[j][0] for j in grp),
            blocks=tuple(chain[j][1] for j in grp),
            block_uses=tuple(per_block_uses[j] for j in grp),
            uses=tuple(uses),
            owned=owned))
    # interval boundaries need ownership contiguous in declaration order
    prev_max = -1
    for seg in segments:
        if not seg.owned:
            continue
        if seg.owned[0] <= prev_max:
            raise MXNetError(
                "segment ownership is not contiguous in parameter "
                f"declaration order (segment {seg.index} owns slot "
                f"{seg.owned[0]} after slot {prev_max}); declare "
                "parameters in chain order to use overlap_grads")
        prev_max = seg.owned[-1]
    return SegmentPlan(segments)


# ---------------------------------------------------------------------------
# Per-bucket all-reduce (the non-zero overlap collective; traced under
# shard_map over dp, like zero's reduce_scatter_bucket)
# ---------------------------------------------------------------------------

def allreduce_bucket(flat, axis_name: str, ndp: int,
                     comm_dtype: Optional[str] = None):
    """Cross-replica SUM all-reduce of one flat gradient bucket, fp32 out.

    comm_dtype None: native ``lax.psum`` (XLA schedules the ring — one
    all-reduce instruction per bucket, the unit the latency-hiding
    scheduler hoists). 'bfloat16'/'int8': the reduce phase rides
    zero.reduce_scatter_bucket's compressed wire (fp32 accumulation), and
    the fp32 partial sums all-gather back."""
    if ndp <= 1:
        return flat.astype(jnp.float32)
    if comm_dtype is None:
        return lax.psum(flat, axis_name).astype(jnp.float32)
    shard = _zero.reduce_scatter_bucket(flat, axis_name, ndp, comm_dtype)
    return _zero.all_gather_bucket(shard, axis_name)


def allreduce_wire_bytes(buckets, ndp: int,
                         comm_dtype: Optional[str] = None) -> int:
    """Per-step wire bytes of the per-bucket all-reduces (ring estimate,
    like DataParallelTrainer._grad_allreduce_bytes; the compressed form is
    the reduce-scatter wire plus the fp32 gather-back)."""
    if ndp <= 1:
        return 0
    if comm_dtype is None:
        return sum(b.nbytes * 2 * (ndp - 1) // ndp for b in buckets)
    return (_zero.reduce_scatter_wire_bytes(buckets, ndp, comm_dtype)
            + _zero.all_gather_wire_bytes(buckets, ndp))


# ---------------------------------------------------------------------------
# Eager segment-grad accumulation (host-driven microbatch loops)
# ---------------------------------------------------------------------------

def _segment_vjp_kernel(*donate):
    """``zero._sharded_update_kernel``'s analog for segment-grad carries:
    jit the kernel donating the given argnums, so the running flat
    accumulator a host-driven microbatch loop threads through segment
    backwards aliases its output in place. mxlint's donation-safety pass
    knows this decorator — reading a donated carry (or any view sliced out
    of it) after the call is flagged."""
    def wrap(fn):
        cache = {"jit": None}

        @functools.wraps(fn)
        def call(*args):
            if cache["jit"] is None:
                donating = bool(donate) and _engine.donation_enabled()
                cache["jit"] = jax.jit(
                    fn, donate_argnums=donate if donating else ())
            return cache["jit"](*args)
        call.__wrapped__ = fn
        return call
    return wrap


@_segment_vjp_kernel(0)
def _k_segment_grad_accum(acc, seg_flat):
    """Fold one segment's flat gradient into the fp32 running accumulator;
    the old accumulator buffer is dead afterwards and is donated."""
    return acc + seg_flat.astype(acc.dtype)


def accumulate_segment_grads(acc, seg_flat):
    """Eager helper: ``acc += seg_flat`` with the accumulator donated.
    The returned array REPLACES ``acc`` — keep no other reference to it."""
    return _k_segment_grad_accum(acc, seg_flat)
