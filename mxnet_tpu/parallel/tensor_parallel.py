"""Tensor (Megatron-style) parallelism helpers.

Capability uplift vs the reference (SURVEY.md §2.4: TP "No"). Weights carry
PartitionSpecs on their Parameters; under pjit XLA partitions the matmuls over
the 'tp' axis and inserts the minimal collectives (all-gather / reduce-scatter
over ICI).

Convention for Dense (weight shape = (out, in), y = x @ W.T):
  column-parallel: shard the OUT dim  -> P('tp', None); activation gets 'tp'
  row-parallel:    shard the IN dim   -> P(None, 'tp'); output needs psum
  (XLA derives both from the specs — no manual collectives.)
"""
from __future__ import annotations

import re
from typing import Dict, Optional

from jax import lax
from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from ..gluon.block import Block
from ..gluon.parameter import Parameter
from .. import telemetry as _telem
from .mesh import axis_size as _axis_size


def column_parallel_spec(axis: str = "tp") -> P:
    return P(axis, None)


def row_parallel_spec(axis: str = "tp") -> P:
    return P(None, axis)


def tp_shard_dim(spec: Optional[P], axis: str = "tp") -> Optional[int]:
    """Index of the dimension a Parameter's PartitionSpec shards over `axis`,
    or None when the spec is absent/fully replicated.

    Used by the manual (shard_map) weight-sharded TP path in
    parallel/pipeline.py, which gathers exactly one sharded dim per leaf —
    specs naming any OTHER mesh axis (compute-partitioned layouts for the
    auto-sharding jit path) are rejected so the two TP styles can't be
    mixed inside one manual program."""
    if spec is None:
        return None
    dim = None
    for d, ax in enumerate(spec):
        if ax is None:
            continue
        axes = ax if isinstance(ax, tuple) else (ax,)
        if tuple(axes) != (axis,):
            raise MXNetError(
                f"partition spec {spec} names mesh axis {ax!r}; the manual "
                f"weight-sharded pipeline TP path only supports specs over "
                f"{axis!r}")
        if dim is not None:
            raise MXNetError(
                f"partition spec {spec} shards {axis!r} over two dims; "
                "one sharded dim per leaf")
        dim = d
    return dim


def gather_tp(w, dim: int, axis: str = "tp"):
    """All-gather a weight-sharded leaf's `dim` back to full logical size
    (call INSIDE shard_map, OUTSIDE the differentiated region — the grads
    w.r.t. the gathered array are then bitwise identical on every rank, so
    `slice_tp` recovers this rank's exact update shard with no collective)."""
    return lax.all_gather(w, axis, axis=dim, tiled=True)


def slice_tp(g, dim: int, axis: str = "tp"):
    """This rank's shard of a replicated-identical full gradient along
    `dim` — the inverse of `gather_tp` for the update lane."""
    n = _axis_size(axis)
    shard = g.shape[dim] // n
    return lax.dynamic_slice_in_dim(g, lax.axis_index(axis) * shard, shard,
                                    axis=dim)


def shard_params_megatron(block: Block, rules: Optional[Dict[str, P]] = None,
                          axis: str = "tp"):
    """Attach TP PartitionSpecs by name pattern. Default rules cover the
    transformer blocks in mxnet_tpu.models.bert: qkv/ffn-in column-parallel,
    proj/ffn-out row-parallel, embeddings sharded on vocab."""
    default_rules = {
        r".*(qkv|query|key|value|ffn1|inter|fc1).*weight$": column_parallel_spec(axis),
        r".*(proj|ffn2|output|fc2).*weight$": row_parallel_spec(axis),
        r".*(qkv|query|key|value|ffn1|inter|fc1).*bias$": P(axis),
        r".*word_embed.*weight$": P(axis, None),
    }
    rules = rules or default_rules
    compiled = [(re.compile(k), v) for k, v in rules.items()]
    n = 0
    nbytes = 0
    # structural names ('encoder.layers.0.attn.qkv.weight') — stable and
    # pattern-matchable, unlike the global-counter flat names
    for name, p in block._collect_params_with_prefix().items():
        for pat, spec in compiled:
            if pat.match(name):
                p.sharding = spec
                n += 1
                nbytes += _telem.payload_bytes(p._data)
                break
    if _telem._ENABLED:
        # footprint that will ride the TP collectives (all-gather /
        # reduce-scatter) once the specs take effect under jit
        _telem.gauge("mx_tp_sharded_params",
                     "Parameters carrying TP PartitionSpecs").set(n)
        _telem.counter("mx_tp_sharded_bytes_total",
                       "Bytes of parameters annotated for TP sharding") \
            .inc(nbytes)
    return n
