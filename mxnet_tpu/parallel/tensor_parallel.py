"""Tensor (Megatron-style) parallelism helpers.

Capability uplift vs the reference (SURVEY.md §2.4: TP "No"). Weights carry
PartitionSpecs on their Parameters; under pjit XLA partitions the matmuls over
the 'tp' axis and inserts the minimal collectives (all-gather / reduce-scatter
over ICI).

Convention for Dense (weight shape = (out, in), y = x @ W.T):
  column-parallel: shard the OUT dim  -> P('tp', None); activation gets 'tp'
  row-parallel:    shard the IN dim   -> P(None, 'tp'); output needs psum
  (XLA derives both from the specs — no manual collectives.)
"""
from __future__ import annotations

import re
from typing import Dict, Optional

from jax import lax
from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from ..gluon.block import Block
from ..gluon.parameter import Parameter
from .. import telemetry as _telem
from .mesh import axis_size as _axis_size


def column_parallel_spec(axis: str = "tp") -> P:
    return P(axis, None)


def row_parallel_spec(axis: str = "tp") -> P:
    return P(None, axis)


def tp_shard_dim(spec: Optional[P], axis: str = "tp") -> Optional[int]:
    """Index of the dimension a Parameter's PartitionSpec shards over `axis`,
    or None when the spec is absent/fully replicated.

    Used by the manual (shard_map) weight-sharded TP path in
    parallel/pipeline.py, which gathers exactly one sharded dim per leaf —
    specs naming any OTHER mesh axis (compute-partitioned layouts for the
    auto-sharding jit path) are rejected so the two TP styles can't be
    mixed inside one manual program."""
    if spec is None:
        return None
    dim = None
    for d, ax in enumerate(spec):
        if ax is None:
            continue
        axes = ax if isinstance(ax, tuple) else (ax,)
        if tuple(axes) != (axis,):
            raise MXNetError(
                f"partition spec {spec} names mesh axis {ax!r}; the manual "
                f"weight-sharded pipeline TP path only supports specs over "
                f"{axis!r}")
        if dim is not None:
            raise MXNetError(
                f"partition spec {spec} shards {axis!r} over two dims; "
                "one sharded dim per leaf")
        dim = d
    return dim


def gather_tp(w, dim: int, axis: str = "tp"):
    """All-gather a weight-sharded leaf's `dim` back to full logical size
    (call INSIDE shard_map, OUTSIDE the differentiated region — the grads
    w.r.t. the gathered array are then bitwise identical on every rank, so
    `slice_tp` recovers this rank's exact update shard with no collective)."""
    return lax.all_gather(w, axis, axis=dim, tiled=True)


def slice_tp(g, dim: int, axis: str = "tp"):
    """This rank's shard of a replicated-identical full gradient along
    `dim` — the inverse of `gather_tp` for the update lane."""
    n = _axis_size(axis)
    shard = g.shape[dim] // n
    return lax.dynamic_slice_in_dim(g, lax.axis_index(axis) * shard, shard,
                                    axis=dim)


# Declarative logical-axis layout table (the SNIPPETS DEFAULT_RULES shape).
# Keys are LOGICAL tensor roles; values name the mesh axis that shards them
# (None = replicated). 'seq' -> 'sp' answers the reference table's
# "# TODO: Can we use sequence parallel?" — with compute-partitioned TP
# (parallel/megatron.py) the non-matmul regions shard the sequence axis
# over the same device group, so the role maps to the 'sp' alias of the tp
# axis group. 'batch' / 'seq' are ACTIVATION roles: validated against the
# mesh like the rest, but apply_rules attaches only the parameter roles.
DEFAULT_RULES: Dict[str, Optional[str]] = {
    "batch": "dp",
    "vocab": "tp",
    "embed": None,
    "heads": "tp",
    "kv": "tp",
    "joined_kv": "tp",
    "mlp": "tp",
    "seq": "sp",
}

# logical role -> name patterns + which positional axis of the weight the
# role occupies (Dense weights are (out, in))
_ROLE_PATTERNS = [
    (r".*(qkv|joined_qkv).*weight$", ("joined_kv", "embed")),
    (r".*(query|key|value|ffn1|inter|fc1).*weight$", ("kv", "embed")),
    (r".*(proj|ffn2|output|fc2).*weight$", ("embed", "mlp")),
    (r".*(qkv|joined_qkv).*bias$", ("joined_kv",)),
    (r".*(query|key|value|ffn1|inter|fc1).*bias$", ("kv",)),
    (r".*(word_embed|decoder).*weight$", ("vocab", "embed")),
    (r".*decoder.*bias$", ("vocab",)),
]


def shard_rules(overrides: Optional[Dict[str, Optional[str]]] = None
                ) -> Dict[str, Optional[str]]:
    """The default logical-role -> mesh-axis table, optionally overridden
    per role. Unknown role names raise (catching typos like 'head')."""
    rules = dict(DEFAULT_RULES)
    if overrides:
        for k in overrides:
            if k not in rules:
                raise MXNetError(
                    f"shard_rules: unknown logical axis {k!r}; known roles: "
                    f"{sorted(rules)}")
        rules.update(overrides)
    return rules


def apply_rules(block: Block, rules: Optional[Dict[str, Optional[str]]] = None,
                mesh=None):
    """Attach PartitionSpecs from a LOGICAL rule table (see DEFAULT_RULES).

    Unlike `shard_params_megatron` (raw name-pattern -> spec), this
    validates every named mesh axis against `mesh.axis_names` and raises a
    clear MXNetError for rules naming a nonexistent axis — a silent no-op
    here means a model silently trains replicated. Returns the number of
    parameters annotated."""
    rules = shard_rules(rules)
    if mesh is not None:
        names = tuple(mesh.axis_names)
        for role, ax in rules.items():
            if ax is not None and ax not in names:
                raise MXNetError(
                    f"apply_rules: rule {role!r} -> {ax!r} names a mesh "
                    f"axis that does not exist (mesh axes: {names}); "
                    "add the axis to make_mesh or set the rule to None")
    compiled = [(re.compile(pat), roles) for pat, roles in _ROLE_PATTERNS]
    n = 0
    nbytes = 0
    for name, p in block._collect_params_with_prefix().items():
        for pat, roles in compiled:
            if pat.match(name):
                spec = P(*(rules.get(r) for r in roles))
                if any(s is not None for s in spec):
                    p.sharding = spec
                    n += 1
                    nbytes += _telem.payload_bytes(p._data)
                break
    if _telem._ENABLED:
        _telem.gauge("mx_tp_sharded_params",
                     "Parameters carrying TP PartitionSpecs").set(n)
        _telem.counter("mx_tp_sharded_bytes_total",
                       "Bytes of parameters annotated for TP sharding") \
            .inc(nbytes)
    return n


def shard_params_megatron(block: Block, rules: Optional[Dict[str, P]] = None,
                          axis: str = "tp"):
    """Attach TP PartitionSpecs by name pattern. Default rules cover the
    transformer blocks in mxnet_tpu.models.bert: qkv/ffn-in column-parallel,
    proj/ffn-out row-parallel, embeddings sharded on vocab."""
    default_rules = {
        r".*(qkv|query|key|value|ffn1|inter|fc1).*weight$": column_parallel_spec(axis),
        r".*(proj|ffn2|output|fc2).*weight$": row_parallel_spec(axis),
        r".*(qkv|query|key|value|ffn1|inter|fc1).*bias$": P(axis),
        r".*word_embed.*weight$": P(axis, None),
    }
    rules = rules or default_rules
    compiled = [(re.compile(k), v) for k, v in rules.items()]
    n = 0
    nbytes = 0
    # structural names ('encoder.layers.0.attn.qkv.weight') — stable and
    # pattern-matchable, unlike the global-counter flat names
    for name, p in block._collect_params_with_prefix().items():
        for pat, spec in compiled:
            if pat.match(name):
                p.sharding = spec
                n += 1
                nbytes += _telem.payload_bytes(p._data)
                break
    if _telem._ENABLED:
        # footprint that will ride the TP collectives (all-gather /
        # reduce-scatter) once the specs take effect under jit
        _telem.gauge("mx_tp_sharded_params",
                     "Parameters carrying TP PartitionSpecs").set(n)
        _telem.counter("mx_tp_sharded_bytes_total",
                       "Bytes of parameters annotated for TP sharding") \
            .inc(nbytes)
    return n
