"""Pipeline parallelism — 1F1B (default) and circular GPipe schedules.

Capability uplift over the reference (SURVEY.md §2.4: the reference has no
pipeline parallelism; its model-parallel story stops at per-layer ctx
placement, reference example/model-parallel-lstm). TPU-native design:

  - both schedules are ONE `lax.scan` inside `shard_map` over the 'pp' mesh
    axis; activations hop stages with `lax.ppermute` (ICI neighbor traffic);
  - **GPipe** (`pipeline_apply`): backward is NOT hand-written —
    differentiating through the scheduled scan runs the transposed schedule.
    Simple, but the transpose stashes one residual per (stage, microbatch):
    peak activation memory grows O(M) with the microbatch count;
  - **1F1B** (`schedule_1f1b`): warmup / steady 1-forward-1-backward /
    cooldown with hand-scheduled per-tick `jax.vjp` segments (plain
    grad-of-scan would replay GPipe order). A microbatch's backward starts
    as soon as its forward clears the last stage, so each stage keeps at
    most 2·pp·v−1 stashed stage inputs regardless of M — peak live
    activations are bounded O(pp) instead of O(M). The optional interleaved
    variant (`virtual_stages=v>1`) gives each device v non-contiguous layer
    chunks (logical stage c·pp+idx), shrinking the bubble fraction from
    (pp−1)/(M+pp−1) toward (pp−1)/(v·M+pp−1) at the cost of v× ppermute
    traffic.

`PipelineTrainer` fuses embed -> schedule -> head -> loss -> backward ->
optimizer update into one jitted shard_map over a mesh with a 'pp' axis,
optionally composed with:

  - a 'dp' axis (pipeline+data parallelism, with `zero_update=True`
    extending the ZeRO-style sharded update + bf16/int8 comm wire of
    parallel/zero.py over the dp axis of the stacked stage params);
  - a 'tp' axis (manual weight-sharded tensor parallelism: leaves carrying
    `Parameter.sharding` specs over 'tp' are stored sharded, all-gathered
    once per step OUTSIDE the differentiated region, and their — then
    rank-identical — grads sliced back for the local update lane).

Executables live in the process-wide engine cache behind a
`StepProgram` keyed on `engine.config_fingerprint()` (parallel/
step_program.py): same-config trainers share compiles and roofline rows.
"""
from __future__ import annotations

from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from .mesh import axis_size as _axis_size, require_axis
from jax.sharding import Mesh, NamedSharding

from ..base import MXNetError, env
from ..ndarray import NDArray
from .. import engine as _engine
from ..engine import async_feed as _feed
from .. import optimizer as opt_mod
from .. import random as _rng
from .. import sanitize as _sanitize
from .. import telemetry as _telem
from . import megatron as _mg
from . import zero as _zero
from .mesh import current_mesh, P
from .step_program import StepProgram
from .tensor_parallel import gather_tp, slice_tp, tp_shard_dim

__all__ = ["pipeline_spec", "pipeline_apply", "gpipe_schedule",
           "schedule_1f1b", "PipelineTrainer"]

env.declare("MXNET_TPU_PP_SCHEDULE", "1f1b", str,
            "Default PipelineTrainer schedule: '1f1b' (bounded activation "
            "memory) or 'gpipe' (grad-of-scan transpose)")


def pipeline_spec(num_stages: int, axis: str = "pp"):
    return {"num_stages": num_stages, "axis": axis}


def pipeline_apply(stage_fn: Callable, stage_params, x_stack,
                   axis_name: str = "pp", remat: bool = True):
    """Differentiable circular pipeline schedule. Call INSIDE shard_map over
    `axis_name`.

    stage_fn(stage_params, x_mb, tick) -> y_mb must be shape-preserving;
    stage_params is THIS device's stage pytree; `tick` is the schedule step
    (traced int32 — fold it into RNG keys so every microbatch draws fresh
    dropout masks); x_stack is the (M, ...) microbatch stack (only stage 0's
    copy is consumed — other stages receive activations over ppermute).
    Returns the (M, ...) output stack, valid on the LAST stage (finite zeros
    elsewhere — inactive ticks compute on zeros and are masked, so no NaNs
    leak and no gradient flows from them).

    Reverse-mode differentiation through this function yields the reverse
    pipeline schedule with weight-gradient accumulation (see module
    docstring) — callers get pipeline backward for free from jax.grad, at
    GPipe's O(M) residual memory. For the bounded-memory hand-scheduled
    alternative see `schedule_1f1b`.
    """
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = x_stack.shape[0]
    steps = M + n - 1
    f = jax.checkpoint(stage_fn) if remat else stage_fn

    def body(inflight, t):
        x_in = jnp.where(idx == 0, x_stack[jnp.clip(t, 0, M - 1)], inflight)
        y = f(stage_params, x_in, t)
        active = jnp.logical_and(t - idx >= 0, t - idx < M)
        y = jnp.where(active, y, jnp.zeros_like(y))
        perm = [(i, (i + 1) % n) for i in range(n)]
        return lax.ppermute(y, axis_name, perm), y

    _, ys = lax.scan(body, jnp.zeros_like(x_stack[0]), jnp.arange(steps))
    # microbatch m leaves the last stage at tick m + n - 1
    return ys[n - 1:]


def gpipe_schedule(stage_fn: Callable, n_microbatch: int, axis_name: str):
    """Back-compat shim over pipeline_apply for parameterless stage fns."""
    def run(x_stack):
        return pipeline_apply(lambda _, x, t: stage_fn(x), (), x_stack,
                              axis_name=axis_name, remat=False)
    return run


def schedule_1f1b(embed_fn: Callable, stage_fn: Callable,
                  head_loss_fn: Callable, eparams, sparams, hparams,
                  x_stack, y_stack, axis_name: str = "pp",
                  n_chunks: int = 1):
    """Hand-scheduled 1F1B/interleaved pipeline. Call INSIDE shard_map over
    `axis_name` (pp). One `lax.scan` over M + 2(pp·v − 1) combined ticks;
    every tick runs one forward lane and one backward lane per chunk, so a
    microbatch's backward begins the tick after its forward clears the last
    logical stage — the steady state is exactly 1-forward-1-backward.

      embed_fn(eparams, x_mb, m)        -> act        (stage-0 entry)
      stage_fn(chunk_leaves, act, tick) -> act        (shape-preserving)
      head_loss_fn(hparams, act, y_mb, m) -> scalar   (mean over microbatch)

    `sparams` leaves are this device's stacked layers (L_local, ...); with
    `n_chunks=v>1` chunk c (rows [c·L_local/v, (c+1)·L_local/v)) acts as
    logical stage c·pp+idx (interleaved schedule — the trainer's
    `_stack_order` lays cell params out in this order). Backward re-derives
    each tick's vjp from the stashed stage INPUT (ring buffer of
    S = 2·pp·v − 1 slots per chunk), so the scan carries O(pp·v) activations
    independent of M — the bounded-memory property GPipe's transposed scan
    lacks. Gradients are masked `jnp.where` sums over microbatches; inactive
    lanes compute on zeros/clamped indices and contribute nothing.

    Returns (loss_sum, grads_embed, grads_stages, grads_head) as
    MICROBATCH SUMS, nonzero only on the owning stage (loss/head: last
    stage; embed: stage 0; stages: local rows). Caller divides by M and
    psums the replicated groups over pp.
    """
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    v = n_chunks
    nv = n * v
    M = x_stack.shape[0]
    T = M + 2 * (nv - 1)
    S = 2 * nv - 1
    Lc = sparams[0].shape[0] // v

    def chunk(c):
        return [w[c * Lc:(c + 1) * Lc] for w in sparams]

    # activation template: one embed fixes shape/dtype for the carries (the
    # value itself is dead — XLA removes the computation)
    act0 = embed_fn(eparams, x_stack[0], jnp.int32(0))
    zact = jnp.zeros(act0.shape, act0.dtype)

    def tick(carry, t):
        fwd_recv, bwd_recv, stash, ge, gs, gh, lsum = carry
        ys_f, new_stash = [], []
        # ---- forward lane: one microbatch per chunk enters/advances ----
        for c in range(v):
            ls = c * n + idx          # logical stage of this chunk
            mf = t - ls               # microbatch in this chunk's forward
            f_act = jnp.logical_and(mf >= 0, mf < M)
            mf_cl = jnp.clip(mf, 0, M - 1)
            if c == 0:
                h_emb = embed_fn(eparams, x_stack[mf_cl], mf_cl)
                x_in = jnp.where(idx == 0, h_emb, fwd_recv[0])
            else:
                x_in = jnp.where(idx == 0, fwd_recv[c - 1], fwd_recv[c])
            yc = stage_fn(chunk(c), x_in, mf_cl + ls)
            ys_f.append(jnp.where(f_act, yc, jnp.zeros_like(yc)))
            upd = lax.dynamic_update_index_in_dim(stash[c], x_in,
                                                  mf_cl % S, 0)
            new_stash.append(jnp.where(f_act, upd, stash[c]))
        # ---- backward lane (reads new_stash: the last stage turns a
        # microbatch around forward->backward within one tick) ----
        dxs = []
        gs2 = [list(g) for g in gs]
        ge2, gh2, lsum2 = list(ge), list(gh), lsum
        for c in range(v):
            ls = c * n + idx
            mb = t - 2 * (nv - 1) + ls  # microbatch in this chunk's backward
            b_act = jnp.logical_and(mb >= 0, mb < M)
            mb_cl = jnp.clip(mb, 0, M - 1)
            x_saved = lax.dynamic_index_in_dim(new_stash[c], mb_cl % S, 0,
                                               keepdims=False)
            if c == v - 1:
                # last chunk: the head+loss vjp seeds the cotangent on the
                # last stage; other stages take the ring-received cotangent
                lv, pull = jax.vjp(
                    lambda hp, h: head_loss_fn(hp, h, y_stack[mb_cl], mb_cl),
                    hparams, ys_f[v - 1])
                gh_c, seed = pull(jnp.ones_like(lv))
                on_last = jnp.logical_and(b_act, idx == n - 1)
                gh2 = [a + jnp.where(on_last, g, 0)
                       for a, g in zip(gh2, gh_c)]
                lsum2 = lsum2 + jnp.where(on_last, lv, jnp.zeros_like(lv))
                out_cot = jnp.where(idx == n - 1, seed, bwd_recv[v - 1])
            else:
                out_cot = jnp.where(idx == n - 1, bwd_recv[c + 1],
                                    bwd_recv[c])
            _, pull_s = jax.vjp(
                lambda ps, h: stage_fn(ps, h, mb_cl + ls), chunk(c), x_saved)
            gw, dx = pull_s(out_cot)
            gs2[c] = [a + jnp.where(b_act, g, 0) for a, g in zip(gs2[c], gw)]
            dx = jnp.where(b_act, dx, jnp.zeros_like(dx))
            if c == 0:
                # chunk 0 on stage 0 owns the embed: pull dx back through it
                _, pull_e = jax.vjp(
                    lambda ep: embed_fn(ep, x_stack[mb_cl], mb_cl), eparams)
                (ge_c,) = pull_e(dx)
                on_first = jnp.logical_and(b_act, idx == 0)
                ge2 = [a + jnp.where(on_first, g, 0)
                       for a, g in zip(ge2, ge_c)]
            dxs.append(dx)
        perm_f = [(i, (i + 1) % n) for i in range(n)]
        perm_b = [(i, (i - 1) % n) for i in range(n)]
        fwd_next = lax.ppermute(jnp.stack(ys_f), axis_name, perm_f)
        bwd_next = lax.ppermute(jnp.stack(dxs), axis_name, perm_b)
        return (fwd_next, bwd_next, new_stash, ge2, gs2, gh2, lsum2), None

    zrecv = jnp.zeros((v,) + zact.shape, zact.dtype)
    carry0 = (zrecv, zrecv,
              [jnp.zeros((S,) + zact.shape, zact.dtype) for _ in range(v)],
              [jnp.zeros_like(w) for w in eparams],
              [[jnp.zeros_like(w) for w in chunk(c)] for c in range(v)],
              [jnp.zeros_like(w) for w in hparams],
              jnp.float32(0.0))
    (_, _, _, ge, gs, gh, lsum), _ = lax.scan(tick, carry0, jnp.arange(T))
    gs_cat = [jnp.concatenate([gs[c][i] for c in range(v)])
              for i in range(len(sparams))]
    return lsum, ge, gs_cat, gh


class PipelineTrainer:
    """Fused pipeline-parallel trainer (optionally composed with data
    parallelism over a 'dp' axis, weight-sharded tensor parallelism over a
    'tp' axis, and the ZeRO-style sharded update over 'dp').

    `net` must expose `pipeline_split() -> (embed, cells, head)` where
    `cells` are structurally identical stateless HybridBlocks (transformer
    encoder layers — models/bert.py grows this method). Cell parameters are
    stacked layerwise into (n_layers, ...) arrays sharded over 'pp'
    (`_stack_order` permutes rows so each device's v interleaved chunks are
    contiguous); embed and head stay replicated over pp, with their
    gradients psum'd over 'pp' (only stage 0 / the last stage produce
    nonzero contributions — the psum is the sync that keeps the replicas
    identical).

    `schedule='1f1b'` (default, MXNET_TPU_PP_SCHEDULE) runs the
    bounded-memory hand-scheduled 1F1B program; `schedule='gpipe'` keeps the
    grad-of-scan transpose. `virtual_stages=v>1` (1F1B only) interleaves v
    layer chunks per device to shrink the pipeline bubble. Frozen
    (grad_req='null') embed/head/cell params skip their update lanes.

    Composition (docs/pipeline_parallel.md):
      - dp_axis:        grads pmean'd over dp (or reduce-scattered, below)
      - zero_update:    ZeRO sharded update over dp — stage buckets carry
                        per-stage (n_stages, padded) state sharded
                        P(pp, dp); requires dp_axis, excludes tp_axis
      - comm_dtype:     bf16/int8 wire for the zero reduce-scatter
      - tp_axis + tp_mode="sharded" (default): leaves with
                        Parameter.sharding specs over 'tp' are STORED
                        sharded (1/tp weight+state memory), all-gathered
                        once per step outside the differentiated region,
                        grads sliced back for the local update lane. The
                        full weight materializes on every rank each step —
                        layer size stays capped at one chip's HBM.
      - tp_axis + tp_mode="partitioned": compute-partitioned (Megatron)
                        TP inside the 1F1B tick body — weights stay
                        sharded forever, manual activation collectives at
                        the region boundaries (parallel/megatron.py).
                        Composes with zero_update (the optimizer state
                        gains a tp dim). `sequence_parallel=True`
                        additionally shards the layernorm/dropout/residual
                        regions along the sequence axis over the same tp
                        device group, turning boundary psums into
                        all_gather/psum_scatter pairs (docs/
                        tensor_parallel.md for the full rule table).

    One jit computes: embed -> schedule -> head -> loss -> backward ->
    collectives -> optimizer update. `loss` must be a mean-reduction
    callable (pred_raw, label_raw) -> scalar so microbatch splitting leaves
    the math identical to a full-batch step.
    """

    def __init__(self, net, loss, optimizer="sgd", optimizer_params=None,
                 mesh: Optional[Mesh] = None, num_microbatch: Optional[int] = None,
                 pp_axis: str = "pp", dp_axis: Optional[str] = None,
                 tp_axis: Optional[str] = None, tp_mode: str = "sharded",
                 sequence_parallel: bool = False,
                 dtype=None, remat: bool = True,
                 schedule: Optional[str] = None, virtual_stages: int = 1,
                 zero_update: Optional[bool] = None,
                 bucket_bytes: Optional[int] = None, comm_dtype=None):
        from .data_parallel import functional_optimizer, _make_apply_fn
        self.net = net
        self.loss = loss
        self.mesh = mesh if mesh is not None else current_mesh()
        self.n_stages = require_axis(self.mesh, pp_axis, "pipeline stages")
        self.pp_axis, self.dp_axis, self.tp_axis = pp_axis, dp_axis, tp_axis
        self.n_dp = require_axis(self.mesh, dp_axis, "data parallelism") \
            if dp_axis else 1
        self.n_tp = require_axis(self.mesh, tp_axis, "tensor parallelism") \
            if tp_axis else 1
        if tp_mode not in ("sharded", "partitioned"):
            raise MXNetError(f"unknown tp_mode {tp_mode!r}; use 'sharded' "
                             "(per-step weight gather) or 'partitioned' "
                             "(compute-partitioned Megatron collectives)")
        if tp_mode == "partitioned" and tp_axis is None:
            raise MXNetError("tp_mode='partitioned' requires a tp_axis")
        self.tp_mode = tp_mode
        self._partitioned = tp_axis is not None and tp_mode == "partitioned"
        self.sequence_parallel = bool(sequence_parallel)
        if self.sequence_parallel and not self._partitioned:
            raise MXNetError(
                "sequence_parallel shards the non-matmul regions over the "
                "tp device group; it requires tp_mode='partitioned'")
        self.remat = remat

        if schedule is None:
            schedule = env.get("MXNET_TPU_PP_SCHEDULE") or "1f1b"
        if schedule not in ("1f1b", "gpipe"):
            raise MXNetError(f"unknown pipeline schedule {schedule!r}; "
                             "use '1f1b' or 'gpipe'")
        self.schedule = schedule
        self.virtual_stages = int(virtual_stages)
        if self.virtual_stages < 1:
            raise MXNetError("virtual_stages must be >= 1")
        if self.virtual_stages > 1 and schedule != "1f1b":
            raise MXNetError("virtual_stages (interleaved schedule) "
                             "requires schedule='1f1b'")
        if self._partitioned and schedule != "1f1b":
            raise MXNetError(
                "tp_mode='partitioned' runs its manual collectives inside "
                "the 1F1B tick body; schedule='gpipe' (grad-of-scan) only "
                "supports weight-sharded tp")

        if not hasattr(net, "pipeline_split"):
            raise MXNetError(
                f"{type(net).__name__} has no pipeline_split(); implement it "
                "returning (embed_block, identical_cells, head_block)")
        embed, cells, head = net.pipeline_split()
        nv = self.n_stages * self.virtual_stages
        if len(cells) % nv != 0:
            raise MXNetError(
                f"{len(cells)} layers do not divide into {self.n_stages} "
                f"pipeline stages x {self.virtual_stages} virtual chunks")
        self.n_layers = len(cells)
        self.layers_per_stage = self.n_layers // self.n_stages

        def _plist(block):
            ps = list(block.collect_params().values())
            if any(p._data is None for p in ps):
                raise MXNetError("net has uninitialized parameters; run one "
                                 "eager forward before PipelineTrainer")
            return ps

        self._embed_plist = _plist(embed)
        self._head_plist = _plist(head)
        self._cell_plists = [_plist(c) for c in cells]
        ref = self._cell_plists[0]
        for j, cp in enumerate(self._cell_plists[1:], 1):
            if len(cp) != len(ref) or any(
                    a._data._data.shape != b._data._data.shape or
                    a._data._data.dtype != b._data._data.dtype
                    for a, b in zip(cp, ref)):
                raise MXNetError(f"cell {j} is not structurally identical to "
                                 "cell 0; pipeline stages must be homogeneous")
        # frozen (grad_req='null') params skip their update lanes; a stacked
        # cell leaf must be uniformly frozen across cells (one update lane
        # serves all layers of the leaf)
        self._tr_e = [p.grad_req != "null" for p in self._embed_plist]
        self._tr_h = [p.grad_req != "null" for p in self._head_plist]
        self._tr_s = [ref[i].grad_req != "null" for i in range(len(ref))]
        for cp in self._cell_plists[1:]:
            for i, p in enumerate(cp):
                if (p.grad_req != "null") != self._tr_s[i]:
                    raise MXNetError(
                        f"cell param {ref[i].name!r} is frozen in some "
                        "layers but not others; freeze a stacked leaf "
                        "uniformly across cells")

        # compute-partitioned TP: structural layer plans decide each leaf's
        # layout (megatron.plan_*); Parameter.sharding specs are NOT read
        # (they may carry auto-sharding specs naming other axes)
        if self._partitioned:
            self._eplan = _mg.plan_embed(embed, self._embed_plist, self.n_tp)
            self._cplan = _mg.plan_cell(cells[0], ref, self.n_tp)
            self._hplan = _mg.plan_head(head, self._head_plist, self.n_tp)
            self._lay_e = self._eplan.layouts
            self._lay_s = self._cplan.layouts
            self._lay_h = self._hplan.layouts
            self._tp_e = [_mg.view_shard_dim(l) for l in self._lay_e]
            self._tp_s = [_mg.view_shard_dim(l) for l in self._lay_s]
            self._tp_h = [_mg.view_shard_dim(l) for l in self._lay_h]
            self._validate_partitioned_loss()
        # manual weight-sharded TP: which dim of each leaf is sharded
        elif tp_axis is not None:
            self._tp_e = [tp_shard_dim(p.sharding, tp_axis)
                          for p in self._embed_plist]
            self._tp_h = [tp_shard_dim(p.sharding, tp_axis)
                          for p in self._head_plist]
            self._tp_s = [tp_shard_dim(ref[i].sharding, tp_axis)
                          for i in range(len(ref))]
            for cp in self._cell_plists[1:]:
                for i, p in enumerate(cp):
                    if tp_shard_dim(p.sharding, tp_axis) != self._tp_s[i]:
                        raise MXNetError(
                            f"cell param {ref[i].name!r} carries different "
                            "tp specs across cells; stacked leaves must "
                            "shard uniformly")
            for plist, dims in ((self._embed_plist, self._tp_e),
                                (self._head_plist, self._tp_h),
                                (ref, self._tp_s)):
                for p, d in zip(plist, dims):
                    if d is not None and \
                            p._data._data.shape[d] % self.n_tp != 0:
                        raise MXNetError(
                            f"{p.name!r} dim {d} ({p._data._data.shape[d]}) "
                            f"does not divide by tp={self.n_tp}")
        else:
            self._tp_e = [None] * len(self._embed_plist)
            self._tp_h = [None] * len(self._head_plist)
            self._tp_s = [None] * len(ref)

        self._embed_apply = _make_apply_fn(embed, self._embed_plist, train=True)
        self._cell_apply = _make_apply_fn(cells[0], ref, train=True)
        self._head_apply = _make_apply_fn(head, self._head_plist, train=True)

        self.compute_dtype = None
        if dtype is not None and jnp.dtype(dtype) != jnp.dtype(jnp.float32):
            self.compute_dtype = jnp.dtype(dtype)
            if self.compute_dtype != jnp.dtype(jnp.bfloat16):
                raise MXNetError("PipelineTrainer supports float32/bfloat16, "
                                 f"got {dtype!r}")

        self.optimizer = optimizer if isinstance(optimizer, opt_mod.Optimizer) \
            else opt_mod.create(optimizer, **(optimizer_params or {}))
        self._init_fn, self._update_fn = functional_optimizer(self.optimizer)

        if num_microbatch is None:
            num_microbatch = self.n_stages
        self.num_microbatch = num_microbatch

        if zero_update is None:
            zero_update = bool(env.get("MXNET_TPU_ZERO"))
        self._zero = bool(zero_update)
        self._bucket_bytes = int(bucket_bytes if bucket_bytes is not None
                                 else env.get("MXNET_TPU_BUCKET_BYTES"))
        if comm_dtype is None:
            comm_dtype = env.get("MXNET_TPU_COMM_DTYPE") or None
        self._comm_dtype = _zero.canonical_comm_dtype(comm_dtype) \
            if self._zero else None
        if self._zero:
            self._validate_zero()
        if tp_axis is not None:
            from ..optimizer.optimizer import LAMB, LARS
            if isinstance(self.optimizer, (LAMB, LARS)):
                raise MXNetError(
                    f"tensor parallelism does not support "
                    f"{type(self.optimizer).__name__}: per-tensor "
                    "trust-ratio norms are wrong on tp shards")

        # interleaved stacking: global row s*L_dev + c*Lc + j holds the
        # params of logical stage c*pp+s, layer j (identity when v == 1)
        Ld, v = self.layers_per_stage, self.virtual_stages
        Lc = Ld // v
        self._stack_order = [(c * self.n_stages + s) * Lc + j
                             for s in range(self.n_stages)
                             for c in range(v) for j in range(Lc)]

        rep = NamedSharding(self.mesh, P())

        def _leaf_sharding(dim, ndim, stacked):
            spec = [None] * (ndim + (1 if stacked else 0))
            if stacked:
                spec[0] = pp_axis
            if dim is not None:
                spec[dim + (1 if stacked else 0)] = tp_axis
            return NamedSharding(self.mesh, P(*spec))

        # storage (VIEW) shapes: identical to the logical shapes except for
        # partitioned leaves with blocked layouts (the fused qkv's (3C, C)
        # stores as (3, C, C) so the tp shard dim is a plain array dim) —
        # tp-degree-independent globals, which is what lets elastic restore
        # reshard tp=2 -> tp=4 with a plain reinstall
        if self._partitioned:
            self._view_e = [
                _mg.view_shape(p._data._data.shape, l)
                for p, l in zip(self._embed_plist, self._lay_e)]
            self._view_h = [
                _mg.view_shape(p._data._data.shape, l)
                for p, l in zip(self._head_plist, self._lay_h)]
            self._view_s = [
                _mg.view_shape(ref[i]._data._data.shape, l)
                for i, l in enumerate(self._lay_s)]
            for views, dims, plist in (
                    (self._view_e, self._tp_e, self._embed_plist),
                    (self._view_h, self._tp_h, self._head_plist),
                    (self._view_s, self._tp_s, ref)):
                for vshape, d, p in zip(views, dims, plist):
                    if d is not None and vshape[d] % self.n_tp != 0:
                        raise MXNetError(
                            f"{p.name!r} partitioned dim {d} "
                            f"({vshape[d]}) does not divide by "
                            f"tp={self.n_tp}")
        else:
            self._view_e = [tuple(p._data._data.shape)
                            for p in self._embed_plist]
            self._view_h = [tuple(p._data._data.shape)
                            for p in self._head_plist]
            self._view_s = [tuple(ref[i]._data._data.shape)
                            for i in range(len(ref))]
        self._e_sh = [_leaf_sharding(d, len(v), False)
                      for v, d in zip(self._view_e, self._tp_e)]
        self._h_sh = [_leaf_sharding(d, len(v), False)
                      for v, d in zip(self._view_h, self._tp_h)]
        self._s_sh = [_leaf_sharding(d, len(v), True)
                      for v, d in zip(self._view_s, self._tp_s)]
        self._e_raw = [
            jax.device_put(
                jnp.array(p._data._data, copy=True).reshape(v), sh)
            for p, v, sh in zip(self._embed_plist, self._view_e, self._e_sh)]
        self._h_raw = [
            jax.device_put(
                jnp.array(p._data._data, copy=True).reshape(v), sh)
            for p, v, sh in zip(self._head_plist, self._view_h, self._h_sh)]
        # layerwise stack in schedule order: leaf i -> (n_layers, ...)
        self._s_raw = [
            jax.device_put(
                jnp.stack([self._cell_plists[m][i]._data._data
                           for m in self._stack_order])
                .reshape((self.n_layers,) + self._view_s[i]), sh)
            for i, sh in enumerate(self._s_sh)]
        # weight-decay indices follow the optimizer's param-idx convention:
        # embed params first, then the stacked cell leaves, then head
        nE, nS = len(self._e_raw), len(self._s_raw)
        self._wd_e = [self.optimizer._get_wd(i) for i in range(nE)]
        self._wd_s = [self.optimizer._get_wd(nE + i) for i in range(nS)]
        self._wd_h = [self.optimizer._get_wd(nE + nS + i)
                      for i in range(len(self._h_raw))]
        if self._zero:
            self._init_zero_state()
        else:
            def _state(w, sh, tr):
                if not tr:
                    return ()
                return jax.tree_util.tree_map(
                    lambda l: jax.device_put(l, sh), self._init_fn(w))
            self._opt_e = [_state(w, sh, tr) for w, sh, tr in
                           zip(self._e_raw, self._e_sh, self._tr_e)]
            self._opt_h = [_state(w, sh, tr) for w, sh, tr in
                           zip(self._h_raw, self._h_sh, self._tr_h)]
            self._opt_s = [_state(w, sh, tr) for w, sh, tr in
                           zip(self._s_raw, self._s_sh, self._tr_s)]
        self._t = 0
        # bounded in-flight dispatch window (engine/async_feed), same
        # contract as DataParallelTrainer: step() stays non-blocking
        self._window = _feed.DispatchWindow(name="pp")
        self._comm_cache = {}   # sig -> (ppermute bytes, calls)
        self._rs_bytes = None
        self._ag_bytes = None
        self._opt_bytes = None
        # process-wide engine-cache key base: N trainers over one model
        # structure and configuration share compiled step artifacts; any
        # change to schedule/microbatching/parallel axes/zero/precision
        # compiles apart (docs/compilation.md "fused-step fingerprints")
        self._step_key_base = (
            "pp_step",
            _engine.structural_fingerprint(net),
            _engine.config_fingerprint(
                optimizer=type(self.optimizer).__name__,
                opt_conf=tuple(sorted(
                    (k, repr(v)) for k, v in vars(self.optimizer).items()
                    if isinstance(v, (int, float, bool, str, type(None))))),
                wds=tuple(float(w) for w in
                          self._wd_e + self._wd_s + self._wd_h),
                loss=self.loss,
                mesh=tuple(sorted(dict(self.mesh.shape).items())),
                axis_order=tuple(self.mesh.axis_names),
                devices=tuple(int(d.id) for d in self.mesh.devices.flat),
                pp_axis=pp_axis, dp_axis=dp_axis, tp_axis=tp_axis,
                schedule=self.schedule,
                virtual_stages=self.virtual_stages,
                num_microbatch=self.num_microbatch,
                remat=self.remat,
                trainable=(tuple(self._tr_e), tuple(self._tr_s),
                           tuple(self._tr_h)),
                tp_dims=(tuple(self._tp_e), tuple(self._tp_s),
                         tuple(self._tp_h)),
                tp_mode=self.tp_mode,
                sequence_parallel=self.sequence_parallel,
                tp_layouts=((tuple(self._lay_e), tuple(self._lay_s),
                             tuple(self._lay_h))
                            if self._partitioned else None),
                compute_dtype=str(self.compute_dtype),
                zero=self._zero,
                bucket_bytes=self._bucket_bytes if self._zero else None,
                comm_dtype=self._comm_dtype))
        self._program = StepProgram(
            f"pp.step[{type(self.net).__name__}]", self._step_key_base)

    def _validate_partitioned_loss(self):
        """The partitioned head FUSES the decoder matmul into the
        vocab-parallel cross-entropy (the full-vocab logits are never
        materialized), so the trainer must know the loss IS mean token
        cross-entropy — any other callable would silently compute the
        wrong thing against the weight-sharded oracle."""
        from ..gluon.loss import SoftmaxCrossEntropyLoss
        lo = self.loss
        if isinstance(lo, SoftmaxCrossEntropyLoss):
            if (getattr(lo, "_sparse_label", True)
                    and not getattr(lo, "_from_logits", False)
                    and getattr(lo, "_axis", -1) in (-1,)
                    and getattr(lo, "_weight", None) is None):
                return
            raise MXNetError(
                "tp_mode='partitioned' fuses the LM head into a "
                "vocab-parallel softmax cross-entropy; "
                "SoftmaxCrossEntropyLoss must use sparse_label=True, "
                "from_logits=False, axis=-1, weight=None")
        if getattr(lo, "__name__", "") == "token_cross_entropy":
            return
        raise MXNetError(
            "tp_mode='partitioned' supports mean token cross-entropy "
            "losses only (gluon SoftmaxCrossEntropyLoss or "
            "recipes.moe.token_cross_entropy); got "
            f"{type(lo).__name__}")

    # -- ZeRO-over-dp composition -------------------------------------------
    def _validate_zero(self):
        if self.dp_axis is None:
            raise MXNetError("zero_update requires a dp_axis: the sharded "
                             "update distributes over data-parallel replicas")
        if self.tp_axis is not None and self.tp_mode != "partitioned":
            raise MXNetError(
                "zero_update and weight-sharded tp_axis do not compose in "
                "PipelineTrainer (the gathered weights would defeat the "
                "sharded state); tp_mode='partitioned' composes — its "
                "optimizer state gains a tp dim")
        from ..optimizer.optimizer import LAMB, LARS
        if isinstance(self.optimizer, (LAMB, LARS)):
            raise MXNetError(
                f"zero_update does not support "
                f"{type(self.optimizer).__name__}: its per-tensor "
                "trust-ratio norms do not decompose over flat bucket "
                "shards; use sgd/adam/adamw/...")

    def _init_zero_state(self):
        """Fusion-bucket plans + dp-sharded optimizer state for the three
        parameter groups. Embed/head buckets mirror the dp trainer exactly
        ((padded,) state sharded P(dp)); stage buckets are planned over the
        LOCAL stacked shapes (identical plan on every stage) with per-stage
        state stacked into (n_stages, padded) arrays sharded P(pp, dp) —
        each (pp, dp) group holds 1/(dp) of its own stage's state."""
        if self._partitioned:
            self._init_zero_state_partitioned()
            return
        dp_sh = NamedSharding(self.mesh, P(self.dp_axis))
        stg_sh = NamedSharding(self.mesh, P(self.pp_axis, self.dp_axis))
        ndp, Ld = self.n_dp, self.layers_per_stage

        def _plan(params, trainables, shapes=None):
            entries = [(i, shapes[i] if shapes else w.shape, w.dtype)
                       for i, (w, tr) in enumerate(zip(params, trainables))
                       if tr and jnp.issubdtype(w.dtype, jnp.floating)]
            return _zero.plan_buckets(entries, ndp, self._bucket_bytes)

        def _flat_carry(plan, params, wds):
            carry = []
            for b in plan:
                flat_w = _zero.flatten_bucket(b, params)
                state = opt_mod.init_functional_state(self._init_fn, flat_w,
                                                      sharding=dp_sh)
                wd_dev = jax.device_put(_zero.wd_vector(b, wds), dp_sh)
                carry.append((wd_dev, state))
            return tuple(carry)

        self._zplan_e = _plan(self._e_raw, self._tr_e)
        self._zplan_h = _plan(self._h_raw, self._tr_h)
        self._opt_e = _flat_carry(self._zplan_e, self._e_raw, self._wd_e)
        self._opt_h = _flat_carry(self._zplan_h, self._h_raw, self._wd_h)
        local_shapes = [(Ld,) + w.shape[1:] for w in self._s_raw]
        self._zplan_s = _plan(self._s_raw, self._tr_s, shapes=local_shapes)
        carry_s = []
        for b in self._zplan_s:
            rows = [_zero.flatten_bucket(
                        b, [w[s * Ld:(s + 1) * Ld] for w in self._s_raw])
                    for s in range(self.n_stages)]
            w_glob = jax.device_put(jnp.stack(rows), stg_sh)
            state = opt_mod.init_functional_state(self._init_fn, w_glob,
                                                  sharding=stg_sh)
            wd_dev = jax.device_put(_zero.wd_vector(b, self._wd_s), dp_sh)
            carry_s.append((wd_dev, state))
        self._opt_s = tuple(carry_s)

    def _init_zero_state_partitioned(self):
        """ZeRO over dp composed with compute-partitioned tp: every
        (pp, tp) rank updates only its OWN weight shard, so the bucket
        plans cover the tp-LOCAL view shapes and the flat state gains a
        leading tp dim — embed/head (n_tp, padded) sharded P(tp, dp),
        stage (n_stages, n_tp, padded) sharded P(pp, tp, dp). The wd
        vectors depend only on the leaf index (identical across tp ranks)
        and stay P(dp)."""
        dp_sh = NamedSharding(self.mesh, P(self.dp_axis))
        tp_sh = NamedSharding(self.mesh, P(self.tp_axis, self.dp_axis))
        stg_sh = NamedSharding(
            self.mesh, P(self.pp_axis, self.tp_axis, self.dp_axis))
        ndp, ntp, Ld = self.n_dp, self.n_tp, self.layers_per_stage

        def _local(shape, d):
            if d is None:
                return tuple(shape)
            return tuple(shape[:d]) + (shape[d] // ntp,) \
                + tuple(shape[d + 1:])

        def _tp_slice(w, d, r):
            if d is None:
                return w
            sz = w.shape[d] // ntp
            return lax.slice_in_dim(w, r * sz, (r + 1) * sz, axis=d)

        def _plan(params, trainables, dims, stacked=False):
            entries = []
            for i, (w, tr, d) in enumerate(zip(params, trainables, dims)):
                if not (tr and jnp.issubdtype(w.dtype, jnp.floating)):
                    continue
                if stacked:
                    shape = _local((Ld,) + w.shape[1:],
                                   d + 1 if d is not None else None)
                else:
                    shape = _local(w.shape, d)
                entries.append((i, shape, w.dtype))
            return _zero.plan_buckets(entries, ndp, self._bucket_bytes)

        self._zplan_e = _plan(self._e_raw, self._tr_e, self._tp_e)
        self._zplan_h = _plan(self._h_raw, self._tr_h, self._tp_h)

        def _flat_tp(plan, params, dims, wds):
            carry = []
            for b in plan:
                rows = [_zero.flatten_bucket(
                            b, [_tp_slice(w, d, r)
                                for w, d in zip(params, dims)])
                        for r in range(ntp)]
                w_glob = jax.device_put(jnp.stack(rows), tp_sh)
                state = opt_mod.init_functional_state(self._init_fn, w_glob,
                                                      sharding=tp_sh)
                wd_dev = jax.device_put(_zero.wd_vector(b, wds), dp_sh)
                carry.append((wd_dev, state))
            return tuple(carry)

        self._opt_e = _flat_tp(self._zplan_e, self._e_raw, self._tp_e,
                               self._wd_e)
        self._opt_h = _flat_tp(self._zplan_h, self._h_raw, self._tp_h,
                               self._wd_h)
        self._zplan_s = _plan(self._s_raw, self._tr_s, self._tp_s,
                              stacked=True)
        carry_s = []
        for b in self._zplan_s:
            rows = [jnp.stack([
                        _zero.flatten_bucket(
                            b, [_tp_slice(w[s * Ld:(s + 1) * Ld],
                                          d + 1 if d is not None else None,
                                          r)
                                for w, d in zip(self._s_raw, self._tp_s)])
                        for r in range(ntp)])
                    for s in range(self.n_stages)]
            w_glob = jax.device_put(jnp.stack(rows), stg_sh)
            state = opt_mod.init_functional_state(self._init_fn, w_glob,
                                                  sharding=stg_sh)
            wd_dev = jax.device_put(_zero.wd_vector(b, self._wd_s), dp_sh)
            carry_s.append((wd_dev, state))
        self._opt_s = tuple(carry_s)

    # ------------------------------------------------------------------
    def _loss_raw(self, pred_raw, label_raw):
        from .data_parallel import DataParallelTrainer
        return DataParallelTrainer._loss_raw(self, pred_raw, label_raw)

    def _build_step(self):
        embed_apply = self._embed_apply
        cell_apply = self._cell_apply
        head_apply = self._head_apply
        update_fn = self._update_fn
        loss_raw = self._loss_raw
        mesh = self.mesh
        ppax, dpax, tpax = self.pp_axis, self.dp_axis, self.tp_axis
        n_stages, M = self.n_stages, self.num_microbatch
        v = self.virtual_stages
        wd_e, wd_s, wd_h = self._wd_e, self._wd_s, self._wd_h
        tr_e, tr_s, tr_h = self._tr_e, self._tr_s, self._tr_h
        tp_e, tp_s, tp_h = self._tp_e, self._tp_s, self._tp_h
        sched, remat = self.schedule, self.remat
        zero, ndp, comm = self._zero, self.n_dp, self._comm_dtype
        cdt = self.compute_dtype
        part, ntp = self._partitioned, self.n_tp
        if part:
            cfg = _mg.PartitionConfig(
                axis=tpax, n_tp=ntp,
                sp=self.sequence_parallel and ntp > 1)
            eplan, cplan, hplan = self._eplan, self._cplan, self._hplan
            lay_e, lay_s, lay_h = self._lay_e, self._lay_s, self._lay_h

        def _low(a):
            if cdt is not None and jnp.issubdtype(a.dtype, jnp.floating):
                return a.astype(cdt)
            return a

        def _no_aux(out_aux, what):
            out, aux = out_aux
            if aux:
                raise MXNetError(
                    f"pipeline {what} emits mutable aux state (BN running "
                    "stats); pipeline stages must be stateless")
            return out

        def body(eparams, sparams, hparams, opt_e, opt_s, opt_h,
                 key, x, y, lr, t):
            # x/y: (M, mb_local, T...) — microbatch stack, batch dim already
            # dp-sliced by shard_map. sparams leaves: (L, ...) local layers.
            idx = lax.axis_index(ppax)
            kk = jax.random.wrap_key_data(key.astype(jnp.uint32),
                                          impl="threefry2x32")
            kk = jax.random.fold_in(kk, idx)
            if dpax is not None:
                kk = jax.random.fold_in(kk, lax.axis_index(dpax))
            # deliberately NOT folded over tp: ranks must draw identical
            # dropout masks so the replicated compute (and the grads being
            # sliced back per rank) stays bitwise identical

            # weight-sharded tp leaves: gather to full size ONCE per step,
            # OUTSIDE the differentiated region — grads w.r.t. the gathered
            # arrays come out rank-identical, no gradient collective needed.
            # (partitioned tp never gathers: the programs below consume the
            # local view shards directly)
            if tpax is not None and not part:
                ep_f = [gather_tp(w, d, tpax) if d is not None else w
                        for w, d in zip(eparams, tp_e)]
                hp_f = [gather_tp(w, d, tpax) if d is not None else w
                        for w, d in zip(hparams, tp_h)]
                sp_f = [gather_tp(w, d + 1, tpax) if d is not None else w
                        for w, d in zip(sparams, tp_s)]
            else:
                ep_f, sp_f, hp_f = eparams, sparams, hparams

            if part:
                def stage_fn(params_local, h, tick):
                    # same (tick, layer) key schedule as the oracle path so
                    # dropout draws line up microbatch-for-microbatch
                    kt = jax.random.fold_in(kk, tick)
                    low = [_low(q) for q in params_local]
                    nloc = params_local[0].shape[0]

                    def cell_body(hc, xs):
                        lp, li = xs
                        klayer = jax.random.fold_in(kt, li)
                        return _mg.cell_forward(cplan, cfg, lp, hc,
                                                klayer), None
                    out, _ = lax.scan(cell_body, h, (low, jnp.arange(nloc)))
                    return out
            else:
                def stage_fn(params_local, h, tick):
                    # fold (tick, layer) so each microbatch draws fresh
                    # dropout masks — tick advances per microbatch in the
                    # schedule
                    kt = jax.random.fold_in(kk, tick)
                    low = [_low(q) for q in params_local]
                    nloc = params_local[0].shape[0]

                    def cell_body(hc, xs):
                        lp, li = xs
                        klayer = jax.random.key_data(
                            jax.random.fold_in(kt, li))
                        return _no_aux(cell_apply(klayer, lp, hc),
                                       "cell"), None
                    out, _ = lax.scan(cell_body, h, (low, jnp.arange(nloc)))
                    return out

            if sched == "1f1b":
                if part:
                    def embed_mb(ep, xm, m):
                        k_e = jax.random.fold_in(
                            jax.random.fold_in(kk, 10_000), m)
                        return _mg.embed_forward(
                            eplan, cfg, [_low(p) for p in ep], xm, k_e)

                    def head_loss_mb(hp, h, ym, m):
                        k_h = jax.random.fold_in(
                            jax.random.fold_in(kk, 10_001), m)
                        return _mg.head_loss_forward(
                            hplan, cfg, [_low(p) for p in hp], h, ym, k_h)
                else:
                    def embed_mb(ep, xm, m):
                        k_e = jax.random.key_data(jax.random.fold_in(
                            jax.random.fold_in(kk, 10_000), m))
                        return _no_aux(embed_apply(k_e,
                                                   [_low(p) for p in ep],
                                                   xm), "embed block")

                    def head_loss_mb(hp, h, ym, m):
                        k_h = jax.random.key_data(jax.random.fold_in(
                            jax.random.fold_in(kk, 10_001), m))
                        logits = _no_aux(head_apply(k_h,
                                                    [_low(p) for p in hp],
                                                    h), "head block")
                        return loss_raw(logits, ym)

                lsum, ge, gs, gh = schedule_1f1b(
                    embed_mb, stage_fn, head_loss_mb, ep_f, sp_f, hp_f,
                    x, y, axis_name=ppax, n_chunks=v)
                # microbatch sums -> batch means (equal microbatch sizes)
                lossv = lsum / M
                ge = [g / M for g in ge]
                gs = [g / M for g in gs]
                gh = [g / M for g in gh]
            else:
                def lossf(ep, sp, hp):
                    k_e = jax.random.key_data(
                        jax.random.fold_in(kk, 10_000))
                    k_h = jax.random.key_data(
                        jax.random.fold_in(kk, 10_001))
                    xf = x.reshape((-1,) + x.shape[2:])
                    h = _no_aux(embed_apply(k_e, [_low(p) for p in ep], xf),
                                "embed block")
                    h = h.reshape((M, -1) + h.shape[1:])
                    out = pipeline_apply(stage_fn, sp, h, axis_name=ppax,
                                         remat=remat)
                    of = out.reshape((-1,) + out.shape[2:])
                    logits = _no_aux(head_apply(k_h, [_low(p) for p in hp],
                                                of), "head block")
                    lossv = loss_raw(logits, y.reshape((-1,) + y.shape[2:]))
                    # only the last stage saw real activations. The mask
                    # must be a plain where — NOT a psum: collectives inside
                    # the differentiated scalar would re-psum the per-device
                    # cotangent seeds and inflate every gradient by
                    # n_stages.
                    return jnp.where(idx == n_stages - 1, lossv, 0.0)

                lossv, (ge, gs, gh) = jax.value_and_grad(
                    lossf, argnums=(0, 1, 2))(ep_f, sp_f, hp_f)
            # loss reporting + replica sync happen OUTSIDE the grad: psum
            # selects the last stage's loss and broadcasts it; embed grads
            # live on stage 0 and head grads on the last stage, so psum over
            # pp is the sync that keeps the replicated copies identical.
            lossv = lax.psum(lossv, ppax)
            if dpax is not None:
                lossv = lax.pmean(lossv, dpax)
            ge = [lax.psum(g, ppax) for g in ge]
            gh = [lax.psum(g, ppax) for g in gh]
            if dpax is not None and not zero:
                # zero mode skips the pmean: the bucket reduce-scatter (+/ndp)
                # below IS the dp mean
                ge = [lax.pmean(g, dpax) for g in ge]
                gs = [lax.pmean(g, dpax) for g in gs]
                gh = [lax.pmean(g, dpax) for g in gh]
            if tpax is not None and not part:
                # grads are rank-identical over tp; each rank updates its
                # own weight shard from its slice — no collective
                ge = [slice_tp(g, d, tpax) if d is not None else g
                      for g, d in zip(ge, tp_e)]
                gh = [slice_tp(g, d, tpax) if d is not None else g
                      for g, d in zip(gh, tp_h)]
                gs = [slice_tp(g, d + 1, tpax) if d is not None else g
                      for g, d in zip(gs, tp_s)]
            elif part and ntp > 1:
                # partial-sum convention (megatron.py docstring): each
                # rank's grad for a REPLICATED leaf is a partial term; one
                # psum over tp completes it. tp-sharded leaves' grads are
                # already the exact local shard — no collective. This runs
                # OUTSIDE the differentiated region, so plain psum is safe.
                ge = [lax.psum(g, tpax) if l is None else g
                      for g, l in zip(ge, lay_e)]
                gh = [lax.psum(g, tpax) if l is None else g
                      for g, l in zip(gh, lay_h)]
                gs = [lax.psum(g, tpax) if l is None else g
                      for g, l in zip(gs, lay_s)]

            if zero:
                pos = lax.axis_index(dpax)

                def zupd(plan, grads, params, carry, lead):
                    # `lead` = number of leading singleton dims carried by
                    # the optimizer-state leaves relative to the plan's flat
                    # buckets: stage states carry the per-stage dim, and the
                    # partitioned-TP variant adds a tp-rank dim in front of
                    # everything (state was built per tp rank over LOCAL view
                    # shapes). Strip them for the update, re-add after.
                    new_p, new_c = list(params), []
                    for b, (wd_vec, st) in zip(plan, carry):
                        stl = st
                        for _ in range(lead):
                            stl = jax.tree_util.tree_map(
                                lambda a: a[0], stl)
                        flat_g = _zero.flatten_bucket(b, grads)
                        g_sh = _zero.reduce_scatter_bucket(
                            flat_g, dpax, ndp, comm) / ndp
                        w_sh = _zero.shard_slice(
                            b, _zero.flatten_bucket(b, params), pos)
                        w2, s2 = update_fn(g_sh.astype(w_sh.dtype), w_sh,
                                           stl, t, lr, wd_vec)
                        full = _zero.all_gather_bucket(
                            w2.astype(w_sh.dtype), dpax)
                        for i, arr in _zero.unflatten_bucket(b, full):
                            new_p[i] = arr.astype(params[i].dtype)
                        for _ in range(lead):
                            s2 = jax.tree_util.tree_map(
                                lambda a: a[None], s2)
                        new_c.append((wd_vec, s2))
                    return new_p, tuple(new_c)

                lead_eh = 1 if part else 0
                eparams, opt_e = zupd(self._zplan_e, ge, eparams, opt_e,
                                      lead_eh)
                hparams, opt_h = zupd(self._zplan_h, gh, hparams, opt_h,
                                      lead_eh)
                sparams, opt_s = zupd(self._zplan_s, gs, sparams, opt_s,
                                      lead_eh + 1)
            else:
                def upd(grads, params, states, wds, trainables):
                    new_p, new_s = [], []
                    for g, w, s, wd, tr in zip(grads, params, states, wds,
                                               trainables):
                        if not tr:
                            new_p.append(w)
                            new_s.append(s)
                            continue
                        w2, s2 = update_fn(g, w, s, t, lr, jnp.float32(wd))
                        new_p.append(w2.astype(w.dtype))
                        new_s.append(s2)
                    return new_p, new_s

                eparams, opt_e = upd(ge, eparams, opt_e, wd_e, tr_e)
                sparams, opt_s = upd(gs, sparams, opt_s, wd_s, tr_s)
                hparams, opt_h = upd(gh, hparams, opt_h, wd_h, tr_h)
            return eparams, sparams, hparams, opt_e, opt_s, opt_h, lossv

        e_in = [sh.spec for sh in self._e_sh]
        s_in = [sh.spec for sh in self._s_sh]
        h_in = [sh.spec for sh in self._h_sh]
        if zero and self._partitioned:
            # partitioned state leaves carry a leading tp-rank dim (plans
            # ran over tp-LOCAL view shapes); wd vectors stay per-dp-shard
            opt_e_in = tuple(
                (P(dpax), jax.tree_util.tree_map(
                    lambda _: P(tpax, dpax), st))
                for (_, st) in self._opt_e)
            opt_h_in = tuple(
                (P(dpax), jax.tree_util.tree_map(
                    lambda _: P(tpax, dpax), st))
                for (_, st) in self._opt_h)
            opt_s_in = tuple(
                (P(dpax), jax.tree_util.tree_map(
                    lambda _: P(ppax, tpax, dpax), st))
                for (_, st) in self._opt_s)
        elif zero:
            opt_e_in = tuple(
                (P(dpax), jax.tree_util.tree_map(lambda _: P(dpax), st))
                for (_, st) in self._opt_e)
            opt_h_in = tuple(
                (P(dpax), jax.tree_util.tree_map(lambda _: P(dpax), st))
                for (_, st) in self._opt_h)
            opt_s_in = tuple(
                (P(dpax), jax.tree_util.tree_map(lambda _: P(ppax, dpax), st))
                for (_, st) in self._opt_s)
        else:
            opt_e_in, opt_s_in, opt_h_in = e_in, s_in, h_in
        data = P(None, dpax) if dpax is not None else P(None)
        rep = P()
        return _zero.shard_map_compat(
            body, mesh=mesh,
            in_specs=(e_in, s_in, h_in, opt_e_in, opt_s_in, opt_h_in,
                      rep, data, data, rep, rep),
            out_specs=(e_in, s_in, h_in, opt_e_in, opt_s_in, opt_h_in, rep))

    def step(self, x, y):
        """One fused pipeline-parallel training step on a global batch."""
        xr = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yr = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        M = self.num_microbatch
        B = xr.shape[0]
        # the loss is a mean: grads are already batch-normalized (same
        # contract as DataParallelTrainer.step, data_parallel.py)
        self.optimizer.rescale_grad = 1.0
        if B % (M * self.n_dp) != 0:
            raise MXNetError(
                f"batch {B} must divide by num_microbatch*dp = {M}*{self.n_dp}")
        if (self._partitioned and self.sequence_parallel and self.n_tp > 1
                and xr.ndim >= 2 and xr.shape[1] % self.n_tp != 0):
            raise MXNetError(
                f"sequence_parallel shards the sequence axis over tp: "
                f"seq_len {xr.shape[1]} must divide by n_tp={self.n_tp}")
        xr = xr.reshape((M, B // M) + xr.shape[1:])
        yr = yr.reshape((M, B // M) + yr.shape[1:])
        sig = (xr.shape, str(xr.dtype), yr.shape, str(yr.dtype))
        # engine cache owns the executable: same-config trainers share one
        # compile (engine.cache_stats()["compiles"] stays flat on the 2nd)
        fn = self._program.get(
            (sig,),
            lambda: jax.jit(self._build_step(),
                            donate_argnums=(0, 1, 2, 3, 4, 5)))
        self._t += 1
        self.optimizer.num_update = self._t
        lr = _np.float32(self.optimizer.learning_rate)
        key = _np.asarray(_rng.next_key_raw())
        data = P(None, self.dp_axis) if self.dp_axis else P(None)
        xr = jax.device_put(xr, NamedSharding(
            self.mesh, P(*data, *([None] * (xr.ndim - 2)))))
        yr = jax.device_put(yr, NamedSharding(
            self.mesh, P(*data, *([None] * (yr.ndim - 2)))))
        # explicit placement of the per-step scalars (sanitize mode's
        # transfer guard rejects implicit numpy->device uploads)
        key, lr, t_in = jax.device_put(
            (key, lr, _np.float32(self._t)),
            NamedSharding(self.mesh, P()))
        call_args = (self._e_raw, self._s_raw, self._h_raw, self._opt_e,
                     self._opt_s, self._opt_h, key, xr, yr, lr, t_in)
        self._program.capture_cost(sig, fn, *call_args, kind="pp_step")
        with _telem.annotate("mx.pp.step"), _sanitize.guard():
            (self._e_raw, self._s_raw, self._h_raw, self._opt_e, self._opt_s,
             self._opt_h, lossv) = fn(*call_args)
        # non-blocking dispatch + backpressure on the (i-K)th step;
        # telemetry after admission (completion-paced, sync-free)
        self._window.admit(lossv)
        if _telem._ENABLED:
            self._record_telemetry(sig, B)
        return _feed.PendingScalar(lossv)

    # -- telemetry -----------------------------------------------------------
    def _ppermute_stats(self, sig):
        """Per-step activation-hop volume of the schedule's ppermute rings
        (per-replica wire bytes, both directions). One activation hops
        M + pp·v − 1 ticks per direction under GPipe's scan (+ transpose)
        and M + 2(pp·v − 1) under 1F1B; the interleaved variant moves a
        v-stack per hop. Shapes come from an abstract eval of the embed —
        no device work, cached per signature."""
        st = self._comm_cache.get(sig)
        if st is None:
            x_shape, x_dtype = sig[0], sig[1]
            out, _ = jax.eval_shape(
                self._embed_apply,
                jax.ShapeDtypeStruct((2,), _np.uint32),
                [jax.ShapeDtypeStruct(w.shape, w.dtype)
                 for w in self._e_raw],
                jax.ShapeDtypeStruct(x_shape[1:], x_dtype))
            h = out if not isinstance(out, tuple) else out[0]
            itemsize = self.compute_dtype.itemsize \
                if self.compute_dtype is not None else h.dtype.itemsize
            act_local = int(_np.prod(h.shape)) // self.n_dp * itemsize
            if self._partitioned and self.sequence_parallel and self.n_tp > 1:
                # the residual stream crossing stage boundaries is
                # seq-sharded over tp in SP mode — each ppermute hop moves
                # a T/tp slice (the peak-activation-memory win shows up on
                # the wire too)
                act_local //= self.n_tp
            nv = self.n_stages * self.virtual_stages
            M = self.num_microbatch
            hops = M + 2 * (nv - 1) if self.schedule == "1f1b" \
                else M + nv - 1
            st = (act_local * self.virtual_stages * 2 * hops, 2 * hops)
            self._comm_cache[sig] = st
        return st

    def _record_partitioned_tp_telemetry(self, sig):
        """Per-step activation-collective volume of compute-partitioned TP
        (parallel/megatron.py). Non-SP books psums at region exits/entries
        (axis='tp'); SP books the all_gather/psum_scatter boundary pairs
        (axis='sp' — they shard/unshard the sequence axis). Ring estimate:
        (tp-1)/tp of the full activation per collective; shapes from an
        abstract eval of the embed, cached per signature."""
        st = self._comm_cache.get(("tp", sig))
        if st is None:
            x_shape, x_dtype = sig[0], sig[1]
            out, _ = jax.eval_shape(
                self._embed_apply,
                jax.ShapeDtypeStruct((2,), _np.uint32),
                [jax.ShapeDtypeStruct(w.shape, w.dtype)
                 for w in self._e_raw],
                jax.ShapeDtypeStruct(x_shape[1:], x_dtype))
            h = out if not isinstance(out, tuple) else out[0]
            itemsize = self.compute_dtype.itemsize \
                if self.compute_dtype is not None else h.dtype.itemsize
            act_full = int(_np.prod(h.shape)) // self.n_dp * itemsize
            wire = act_full * (self.n_tp - 1) // self.n_tp
            M = self.num_microbatch
            L = self.n_layers
            if self.sequence_parallel:
                # each region boundary is an all_gather (enter) +
                # psum_scatter (exit) pair, and autodiff mirrors each as
                # its dual: 2L+1 region boundaries (2 per cell, embed exit
                # + head entry share one), ×2 for fwd+bwd
                calls = M * (2 * L + 1) * 2
                st = (("tp_act_all_gather", wire * calls, calls, "sp"),
                      ("tp_act_psum_scatter", wire * calls, calls, "sp"))
            else:
                # per cell: reduce_from_tp fwd psum ×2 regions +
                # copy_to_tp bwd psum ×2 regions; +2 for embed exit psum
                # and the head entry's bwd psum
                calls = M * (4 * L + 2)
                st = (("tp_act_psum", wire * calls, calls, "tp"),)
            self._comm_cache[("tp", sig)] = st
        for op, nbytes, calls, ax in st:
            _telem.record_comm(op, nbytes, store="mesh", calls=calls, axis=ax)

    def _record_zero_telemetry(self):
        if self._rs_bytes is None:
            plans = self._zplan_e + self._zplan_s + self._zplan_h
            self._rs_bytes = _zero.reduce_scatter_wire_bytes(
                plans, self.n_dp, self._comm_dtype)
            self._ag_bytes = _zero.all_gather_wire_bytes(plans, self.n_dp)
        nb = len(self._zplan_e) + len(self._zplan_s) + len(self._zplan_h)
        _telem.record_comm("reduce_scatter", self._rs_bytes, store="mesh",
                           calls=nb, axis="dp")
        _telem.record_comm("all_gather", self._ag_bytes, store="mesh",
                           calls=nb, axis="dp")

    def _opt_state_replica_bytes(self) -> int:
        if self._opt_bytes is None:
            tree = (self._opt_e, self._opt_s, self._opt_h)
            if self._zero:
                # wd vectors riding the bucket carries are hyperparameter
                # constants, not optimizer state
                tree = tuple([st for _, st in grp] for grp in tree)
            self._opt_bytes = _zero.per_replica_state_bytes(tree)
        return self._opt_bytes

    def _record_telemetry(self, sig, examples):
        cost = self._program.cost(sig)
        flops = cost.get("flops")
        if self.n_stages > 1:
            # per-step collective volume: the schedule's activation-hop
            # ppermute rings + the embed/head grad psum over 'pp'
            pp_bytes, pp_calls = self._ppermute_stats(sig)
            _telem.record_comm("ppermute", pp_bytes, store="mesh",
                               calls=pp_calls, axis="pp")
            rep_bytes = sum(int(w.nbytes) for w in
                            self._e_raw + self._h_raw)
            _telem.record_comm("pipeline_grad_psum", rep_bytes, store="mesh",
                               axis="pp")
        if self._zero:
            self._record_zero_telemetry()
        if self.tp_axis is not None and self.n_tp > 1 and not self._partitioned:
            # per-step weight all-gather of the tp-sharded leaves
            # (ring estimate: (tp-1)/tp of the full footprint)
            ag = sum(int(w.nbytes) * (self.n_tp - 1) // self.n_tp
                     for w, d in zip(self._e_raw + self._s_raw + self._h_raw,
                                     self._tp_e + self._tp_s + self._tp_h)
                     if d is not None)
            _telem.record_comm("tp_weight_all_gather", ag, store="mesh",
                               axis="tp")
        elif self._partitioned and self.n_tp > 1:
            # partitioned mode NEVER gathers weights: its collectives move
            # activations only. Booking them under a separate op/axis lane
            # is what lets tests assert "no weight gather" from the ledger.
            self._record_partitioned_tp_telemetry(sig)
        _telem.record_optimizer_state(self._opt_state_replica_bytes(),
                                      source="pipeline")
        # roofline ledger + aggregate flops/bytes through the one engine
        # funnel (after window admission: completion-paced); the region is
        # the fingerprint-derived StepProgram row, like DP
        _engine.record_execution(
            "step", flops or 0.0,
            bytes_accessed=cost.get("bytes_accessed", 0.0),
            region=self._program.region(sig), cost=cost)
        from ..telemetry import goodput as _goodput
        if _goodput._ENABLED and self.n_stages > 1:
            # analytic schedule bubble: idle ticks over total ticks for
            # this schedule's tick count (the same counts _ppermute_stats
            # uses); the ledger multiplies it into the measured
            # device-bound share of each step (the tick slope)
            nv = self.n_stages * self.virtual_stages
            M = self.num_microbatch
            ticks = M + 2 * (nv - 1) if self.schedule == "1f1b" \
                else M + nv - 1
            _goodput.set_pipeline_bubble("pipeline", (ticks - M) / ticks)
        _telem.record_step(examples, source="pipeline", flops_per_step=flops,
                           lr=float(self.optimizer.learning_rate),
                           dispatch_wait_seconds=self._window.wait_seconds)

    def drain(self):
        """Block until every dispatched step completed (epoch/eval
        boundary drain point)."""
        self._window.drain()

    def sync(self):
        """Write device params back into the gluon Parameters (unstacking
        the layerwise cell stacks through `_stack_order`). Row slices are
        device-side views — one (lazy) transfer per leaf at most, never a
        host round-trip per layer."""
        self.drain()
        if self._partitioned:
            # view-shaped storage (blocked qkv etc.) folds back to the
            # Parameters' logical shapes
            for p, w in zip(self._embed_plist, self._e_raw):
                p._data._set_data(w.reshape(p.shape))
            for p, w in zip(self._head_plist, self._h_raw):
                p._data._set_data(w.reshape(p.shape))
            for i, w in enumerate(self._s_raw):
                for k, m in enumerate(self._stack_order):
                    p = self._cell_plists[m][i]
                    p._data._set_data(w[k].reshape(p.shape))
            return
        for p, w in zip(self._embed_plist, self._e_raw):
            p._data._set_data(w)
        for p, w in zip(self._head_plist, self._h_raw):
            p._data._set_data(w)
        for i, w in enumerate(self._s_raw):
            for k, m in enumerate(self._stack_order):
                self._cell_plists[m][i]._data._set_data(w[k])

    # -- elastic fault tolerance ---------------------------------------------
    def state_dict(self):
        """Full training state in the elastic snapshot schema (embed/stage/
        head params with their stacked layout + stack order, per-replica
        ZeRO shards, RNG, step/schedule counters) — see
        mxnet_tpu/elastic/state.py."""
        from ..elastic import state as _estate
        return _estate.capture(self)

    def load_state_dict(self, snapshot):
        """Install a ``state_dict()``/manifest snapshot, permuting stacked
        stage rows when the (pp, virtual_stages) schedule changed and
        resharding onto this trainer's mesh (docs/checkpointing.md)."""
        from ..elastic import state as _estate
        self.drain()
        leaves, meta = snapshot["leaves"], snapshot["meta"]
        _estate.install(self, meta, leaves.__getitem__, set(leaves))
        return self

    @property
    def num_update(self):
        return self._t
