"""Pipeline parallelism — circular GPipe schedule with full backward.

Capability uplift over the reference (SURVEY.md §2.4: the reference has no
pipeline parallelism; its model-parallel story stops at per-layer ctx
placement, reference example/model-parallel-lstm). TPU-native design:

  - the schedule is ONE `lax.scan` inside `shard_map` over the 'pp' mesh
    axis; activations hop stages with `lax.ppermute` (ICI neighbor traffic);
  - backward is NOT hand-written: differentiating through the scheduled scan
    runs the transposed schedule — scan's transpose replays the steps in
    reverse and ppermute's transpose carries activation cotangents
    last→first stage, while the loop-invariant stage parameters accumulate
    their microbatch-summed weight gradients through scan's cotangent
    accumulation. Forward GPipe + reverse-schedule backward + weight-grad
    accumulation all land in a single XLA computation;
  - per-stage calls run under `jax.checkpoint` by default, so the stashed
    residuals are one activation per (stage, microbatch) — GPipe's memory
    profile — instead of every intermediate inside the stage.

`PipelineTrainer` fuses embed -> pipeline -> head -> loss -> backward ->
optimizer update into one jit over a mesh with a 'pp' axis (optionally
composed with a 'dp' axis for pipeline+data parallelism).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from .mesh import axis_size as _axis_size
from jax.sharding import Mesh, NamedSharding

from ..base import MXNetError
from ..ndarray import NDArray
from .. import engine as _engine
from ..engine import async_feed as _feed
from .. import optimizer as opt_mod
from .. import random as _rng
from .. import sanitize as _sanitize
from .. import telemetry as _telem
from .mesh import current_mesh, P

__all__ = ["pipeline_spec", "pipeline_apply", "gpipe_schedule",
           "PipelineTrainer"]


def pipeline_spec(num_stages: int, axis: str = "pp"):
    return {"num_stages": num_stages, "axis": axis}


def pipeline_apply(stage_fn: Callable, stage_params, x_stack,
                   axis_name: str = "pp", remat: bool = True):
    """Differentiable circular pipeline schedule. Call INSIDE shard_map over
    `axis_name`.

    stage_fn(stage_params, x_mb, tick) -> y_mb must be shape-preserving;
    stage_params is THIS device's stage pytree; `tick` is the schedule step
    (traced int32 — fold it into RNG keys so every microbatch draws fresh
    dropout masks); x_stack is the (M, ...) microbatch stack (only stage 0's
    copy is consumed — other stages receive activations over ppermute).
    Returns the (M, ...) output stack, valid on the LAST stage (finite zeros
    elsewhere — inactive ticks compute on zeros and are masked, so no NaNs
    leak and no gradient flows from them).

    Reverse-mode differentiation through this function yields the reverse
    pipeline schedule with weight-gradient accumulation (see module
    docstring) — callers get pipeline backward for free from jax.grad.
    """
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = x_stack.shape[0]
    steps = M + n - 1
    f = jax.checkpoint(stage_fn) if remat else stage_fn

    def body(inflight, t):
        x_in = jnp.where(idx == 0, x_stack[jnp.clip(t, 0, M - 1)], inflight)
        y = f(stage_params, x_in, t)
        active = jnp.logical_and(t - idx >= 0, t - idx < M)
        y = jnp.where(active, y, jnp.zeros_like(y))
        perm = [(i, (i + 1) % n) for i in range(n)]
        return lax.ppermute(y, axis_name, perm), y

    _, ys = lax.scan(body, jnp.zeros_like(x_stack[0]), jnp.arange(steps))
    # microbatch m leaves the last stage at tick m + n - 1
    return ys[n - 1:]


def gpipe_schedule(stage_fn: Callable, n_microbatch: int, axis_name: str):
    """Back-compat shim over pipeline_apply for parameterless stage fns."""
    def run(x_stack):
        return pipeline_apply(lambda _, x, t: stage_fn(x), (), x_stack,
                              axis_name=axis_name, remat=False)
    return run


class PipelineTrainer:
    """Fused pipeline-parallel trainer (optionally composed with data
    parallelism over a 'dp' mesh axis).

    `net` must expose `pipeline_split() -> (embed, cells, head)` where
    `cells` are structurally identical stateless HybridBlocks (transformer
    encoder layers — models/bert.py grows this method). Cell parameters are
    stacked layerwise into (n_layers, ...) arrays sharded over 'pp'
    (layers_per_stage = n_layers / pp); embed and head stay replicated, with
    their gradients psum'd over 'pp' (only stage 0 / the last stage produce
    nonzero contributions — the psum is the sync that keeps the replicas
    identical).

    One jit computes: embed -> circular GPipe schedule (pipeline_apply) ->
    head -> loss -> reverse-schedule backward -> optimizer update, with the
    cross-'dp' gradient pmean inserted explicitly when dp > 1. `loss` must be
    a mean-reduction callable (pred_raw, label_raw) -> scalar so microbatch
    splitting leaves the math identical to a full-batch step.
    """

    def __init__(self, net, loss, optimizer="sgd", optimizer_params=None,
                 mesh: Optional[Mesh] = None, num_microbatch: Optional[int] = None,
                 pp_axis: str = "pp", dp_axis: Optional[str] = None,
                 dtype=None, remat: bool = True):
        from .data_parallel import functional_optimizer, _make_apply_fn
        self.net = net
        self.loss = loss
        self.mesh = mesh if mesh is not None else current_mesh()
        if pp_axis not in self.mesh.shape:
            raise MXNetError(f"mesh has no {pp_axis!r} axis: {self.mesh.shape}")
        if dp_axis is not None and dp_axis not in self.mesh.shape:
            raise MXNetError(f"mesh has no {dp_axis!r} axis: {self.mesh.shape}")
        self.pp_axis, self.dp_axis = pp_axis, dp_axis
        self.n_stages = self.mesh.shape[pp_axis]
        self.n_dp = self.mesh.shape[dp_axis] if dp_axis else 1
        self.remat = remat

        if not hasattr(net, "pipeline_split"):
            raise MXNetError(
                f"{type(net).__name__} has no pipeline_split(); implement it "
                "returning (embed_block, identical_cells, head_block)")
        embed, cells, head = net.pipeline_split()
        if len(cells) % self.n_stages != 0:
            raise MXNetError(
                f"{len(cells)} layers do not divide into {self.n_stages} "
                "pipeline stages")
        self.n_layers = len(cells)
        self.layers_per_stage = self.n_layers // self.n_stages

        def _plist(block):
            ps = list(block.collect_params().values())
            if any(p._data is None for p in ps):
                raise MXNetError("net has uninitialized parameters; run one "
                                 "eager forward before PipelineTrainer")
            return ps

        self._embed_plist = _plist(embed)
        self._head_plist = _plist(head)
        self._cell_plists = [_plist(c) for c in cells]
        ref = self._cell_plists[0]
        for j, cp in enumerate(self._cell_plists[1:], 1):
            if len(cp) != len(ref) or any(
                    a._data._data.shape != b._data._data.shape or
                    a._data._data.dtype != b._data._data.dtype
                    for a, b in zip(cp, ref)):
                raise MXNetError(f"cell {j} is not structurally identical to "
                                 "cell 0; pipeline stages must be homogeneous")
        all_cell_params = [p for cp in self._cell_plists for p in cp]
        for p in self._embed_plist + self._head_plist + all_cell_params:
            if p.grad_req == "null":
                raise MXNetError("frozen (grad_req='null') parameters are not "
                                 "supported in PipelineTrainer yet")

        self._embed_apply = _make_apply_fn(embed, self._embed_plist, train=True)
        self._cell_apply = _make_apply_fn(cells[0], ref, train=True)
        self._head_apply = _make_apply_fn(head, self._head_plist, train=True)

        self.compute_dtype = None
        if dtype is not None and jnp.dtype(dtype) != jnp.dtype(jnp.float32):
            self.compute_dtype = jnp.dtype(dtype)
            if self.compute_dtype != jnp.dtype(jnp.bfloat16):
                raise MXNetError("PipelineTrainer supports float32/bfloat16, "
                                 f"got {dtype!r}")

        self.optimizer = optimizer if isinstance(optimizer, opt_mod.Optimizer) \
            else opt_mod.create(optimizer, **(optimizer_params or {}))
        self._init_fn, self._update_fn = functional_optimizer(self.optimizer)

        if num_microbatch is None:
            num_microbatch = self.n_stages
        self.num_microbatch = num_microbatch

        rep = NamedSharding(self.mesh, P())
        stk = NamedSharding(self.mesh, P(pp_axis))
        self._e_raw = [jax.device_put(jnp.array(p._data._data, copy=True), rep)
                       for p in self._embed_plist]
        self._h_raw = [jax.device_put(jnp.array(p._data._data, copy=True), rep)
                       for p in self._head_plist]
        # layerwise stack: leaf i -> (n_layers, ...) sharded over pp
        self._s_raw = [
            jax.device_put(jnp.stack([cp[i]._data._data
                                      for cp in self._cell_plists]), stk)
            for i in range(len(ref))]
        self._opt_e = [jax.device_put(self._init_fn(w), rep)
                       for w in self._e_raw]
        self._opt_h = [jax.device_put(self._init_fn(w), rep)
                       for w in self._h_raw]
        self._opt_s = [jax.tree_util.tree_map(
            lambda l: jax.device_put(l, stk), self._init_fn(w))
            for w in self._s_raw]
        # weight-decay indices follow the optimizer's param-idx convention:
        # embed params first, then the stacked cell leaves, then head
        nE, nS = len(self._e_raw), len(self._s_raw)
        self._wd_e = [self.optimizer._get_wd(i) for i in range(nE)]
        self._wd_s = [self.optimizer._get_wd(nE + i) for i in range(nS)]
        self._wd_h = [self.optimizer._get_wd(nE + nS + i)
                      for i in range(len(self._h_raw))]
        self._t = 0
        # bounded in-flight dispatch window (engine/async_feed), same
        # contract as DataParallelTrainer: step() stays non-blocking
        self._window = _feed.DispatchWindow(name="pp")
        self._step_jit = {}
        self._step_cost = {}
        self._region_cache = {}  # sig -> roofline ledger row key

    # ------------------------------------------------------------------
    def _loss_raw(self, pred_raw, label_raw):
        from .data_parallel import DataParallelTrainer
        return DataParallelTrainer._loss_raw(self, pred_raw, label_raw)

    def _build_step(self):
        embed_apply = self._embed_apply
        cell_apply = self._cell_apply
        head_apply = self._head_apply
        update_fn = self._update_fn
        loss_raw = self._loss_raw
        mesh, ppax, dpax = self.mesh, self.pp_axis, self.dp_axis
        n_stages, L, M = self.n_stages, self.layers_per_stage, self.num_microbatch
        wd_e, wd_s, wd_h = self._wd_e, self._wd_s, self._wd_h
        remat = self.remat
        cdt = self.compute_dtype

        def _low(a):
            if cdt is not None and jnp.issubdtype(a.dtype, jnp.floating):
                return a.astype(cdt)
            return a

        def _no_aux(out_aux, what):
            out, aux = out_aux
            if aux:
                raise MXNetError(
                    f"pipeline {what} emits mutable aux state (BN running "
                    "stats); pipeline stages must be stateless")
            return out

        def body(eparams, sparams, hparams, opt_e, opt_s, opt_h,
                 key, x, y, lr, t):
            # x/y: (M, mb_local, T...) — microbatch stack, batch dim already
            # dp-sliced by shard_map. sparams leaves: (L, ...) local layers.
            idx = lax.axis_index(ppax)
            kk = jax.random.wrap_key_data(key.astype(jnp.uint32),
                                          impl="threefry2x32")
            kk = jax.random.fold_in(kk, idx)
            if dpax is not None:
                kk = jax.random.fold_in(kk, lax.axis_index(dpax))

            def stage_fn(params_local, h, tick):
                # fold (tick, layer) so each microbatch draws fresh dropout
                # masks — tick advances per microbatch in the schedule
                kt = jax.random.fold_in(kk, tick)

                def cell_body(hc, xs):
                    lp, li = xs
                    klayer = jax.random.key_data(jax.random.fold_in(kt, li))
                    return _no_aux(cell_apply(klayer, lp, hc), "cell"), None
                out, _ = lax.scan(cell_body, h, (params_local, jnp.arange(L)))
                return out

            def lossf(ep, sp, hp):
                k_e = jax.random.key_data(jax.random.fold_in(kk, 10_000))
                k_h = jax.random.key_data(jax.random.fold_in(kk, 10_001))
                xf = x.reshape((-1,) + x.shape[2:])
                h = _no_aux(embed_apply(k_e, [_low(p) for p in ep], xf),
                            "embed block")
                h = h.reshape((M, -1) + h.shape[1:])
                out = pipeline_apply(
                    lambda p, hx, t_: stage_fn([_low(q) for q in p], hx, t_),
                    sp, h, axis_name=ppax, remat=remat)
                of = out.reshape((-1,) + out.shape[2:])
                logits = _no_aux(head_apply(k_h, [_low(p) for p in hp], of),
                                 "head block")
                lossv = loss_raw(logits, y.reshape((-1,) + y.shape[2:]))
                # only the last stage saw real activations. The mask must be
                # a plain where — NOT a psum: collectives inside the
                # differentiated scalar would re-psum the per-device
                # cotangent seeds and inflate every gradient by n_stages.
                return jnp.where(idx == n_stages - 1, lossv, 0.0)

            lossv, (ge, gs, gh) = jax.value_and_grad(
                lossf, argnums=(0, 1, 2))(eparams, sparams, hparams)
            # loss reporting + replica sync happen OUTSIDE the grad: psum
            # selects the last stage's loss and broadcasts it; embed grads
            # live on stage 0 and head grads on the last stage, so psum over
            # pp is the sync that keeps the replicated copies identical.
            lossv = lax.psum(lossv, ppax)
            if dpax is not None:
                lossv = lax.pmean(lossv, dpax)
            ge = [lax.psum(g, ppax) for g in ge]
            gh = [lax.psum(g, ppax) for g in gh]
            if dpax is not None:
                ge = [lax.pmean(g, dpax) for g in ge]
                gs = [lax.pmean(g, dpax) for g in gs]
                gh = [lax.pmean(g, dpax) for g in gh]

            def upd(grads, params, states, wds):
                new_p, new_s = [], []
                for g, w, s, wd in zip(grads, params, states, wds):
                    w2, s2 = update_fn(g, w, s, t, lr, jnp.float32(wd))
                    new_p.append(w2.astype(w.dtype))
                    new_s.append(s2)
                return new_p, new_s

            eparams, opt_e = upd(ge, eparams, opt_e, wd_e)
            sparams, opt_s = upd(gs, sparams, opt_s, wd_s)
            hparams, opt_h = upd(gh, hparams, opt_h, wd_h)
            return eparams, sparams, hparams, opt_e, opt_s, opt_h, lossv

        rep, stk = P(), P(ppax)
        data = P(None, dpax) if dpax is not None else P(None)
        from .zero import shard_map_compat
        return shard_map_compat(
            body, mesh=mesh,
            in_specs=(rep, stk, rep, rep, stk, rep, rep, data, data, rep, rep),
            out_specs=(rep, stk, rep, rep, stk, rep, rep))

    def step(self, x, y):
        """One fused pipeline-parallel training step on a global batch."""
        xr = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yr = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        M = self.num_microbatch
        B = xr.shape[0]
        # the loss is a mean: grads are already batch-normalized (same
        # contract as DataParallelTrainer.step, data_parallel.py)
        self.optimizer.rescale_grad = 1.0
        if B % (M * self.n_dp) != 0:
            raise MXNetError(
                f"batch {B} must divide by num_microbatch*dp = {M}*{self.n_dp}")
        xr = xr.reshape((M, B // M) + xr.shape[1:])
        yr = yr.reshape((M, B // M) + yr.shape[1:])
        sig = (xr.shape, str(xr.dtype), yr.shape, str(yr.dtype))
        fn = self._step_jit.get(sig)
        if fn is None:
            fn = jax.jit(self._build_step(),
                         donate_argnums=(0, 1, 2, 3, 4, 5))
            self._step_jit[sig] = fn
        self._t += 1
        self.optimizer.num_update = self._t
        lr = _np.float32(self.optimizer.learning_rate)
        key = _np.asarray(_rng.next_key_raw())
        data = P(None, self.dp_axis) if self.dp_axis else P(None)
        xr = jax.device_put(xr, NamedSharding(
            self.mesh, P(*data, *([None] * (xr.ndim - 2)))))
        yr = jax.device_put(yr, NamedSharding(
            self.mesh, P(*data, *([None] * (yr.ndim - 2)))))
        # explicit placement of the per-step scalars (sanitize mode's
        # transfer guard rejects implicit numpy->device uploads)
        key, lr, t_in = jax.device_put(
            (key, lr, _np.float32(self._t)),
            NamedSharding(self.mesh, P()))
        call_args = (self._e_raw, self._s_raw, self._h_raw, self._opt_e,
                     self._opt_s, self._opt_h, key, xr, yr, lr, t_in)
        if _telem._ENABLED and sig not in self._step_cost:
            self._step_cost[sig] = _engine.estimate_cost(fn, *call_args,
                                                         kind="pp_step")
        with _telem.annotate("mx.pp.step"), _sanitize.guard():
            (self._e_raw, self._s_raw, self._h_raw, self._opt_e, self._opt_s,
             self._opt_h, lossv) = fn(*call_args)
        # non-blocking dispatch + backpressure on the (i-K)th step;
        # telemetry after admission (completion-paced, sync-free)
        self._window.admit(lossv)
        if _telem._ENABLED:
            # per-step collective volume: the embed/head grad psum over 'pp'
            # (the stage-hop ppermute traffic is activation-shaped and
            # schedule-dependent; the psum'd replicated params dominate)
            if self.n_stages > 1:
                rep_bytes = sum(int(w.nbytes) for w in
                                self._e_raw + self._h_raw)
                _telem.record_comm("pipeline_grad_psum", rep_bytes,
                                   store="mesh")
            cost = self._step_cost.get(sig, {})
            flops = cost.get("flops")
            region = self._region_cache.get(sig)
            if region is None:
                import hashlib
                digest = hashlib.sha1(repr(("pp_step", self.n_stages,
                                            self.num_microbatch,
                                            sig)).encode()).hexdigest()
                region = self._region_cache[sig] = f"pp.step#{digest[:6]}"
            # roofline ledger + aggregate flops/bytes through the one
            # engine funnel (after window admission: completion-paced)
            _engine.record_execution(
                "step", flops or 0.0,
                bytes_accessed=cost.get("bytes_accessed", 0.0),
                region=region, cost=cost)
            _telem.record_step(B, source="pipeline", flops_per_step=flops,
                               lr=float(self.optimizer.learning_rate))
        return _feed.PendingScalar(lossv)

    def drain(self):
        """Block until every dispatched step completed (epoch/eval
        boundary drain point)."""
        self._window.drain()

    def sync(self):
        """Write device params back into the gluon Parameters (unstacking
        the layerwise cell stacks)."""
        self.drain()
        for p, w in zip(self._embed_plist, self._e_raw):
            p._data._set_data(w)
        for p, w in zip(self._head_plist, self._h_raw):
            p._data._set_data(w)
        for i, w in enumerate(self._s_raw):
            host = _np.asarray(w)
            for j, cp in enumerate(self._cell_plists):
                cp[i]._data._set_data(jnp.asarray(host[j]))

    @property
    def num_update(self):
        return self._t
