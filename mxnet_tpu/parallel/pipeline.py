"""Pipeline parallelism scaffolding (SURVEY.md §2.4: PP "No" in reference).

Round-1 surface: stage specs + a microbatched GPipe-style schedule helper
usable inside shard_map over a 'pp' axis. The full pipeline trainer (1F1B
schedule fused with dp/tp) lands in a later round.
"""
from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_spec(num_stages: int, axis: str = "pp"):
    return {"num_stages": num_stages, "axis": axis}


def gpipe_schedule(stage_fn: Callable, n_microbatch: int, axis_name: str):
    """Run stage_fn over microbatches inside shard_map over `axis_name`.

    stage_fn(carry, x_mb) -> y_mb for the local stage; activations move to the
    next stage with ppermute each tick. Returns a function mapping the local
    microbatch stack (M, ...) -> output stack for the last stage.
    """
    def run(x_stack):
        n = lax.axis_size(axis_name)
        idx = lax.axis_index(axis_name)
        M = x_stack.shape[0]
        steps = M + n - 1
        buf = jnp.zeros_like(x_stack)

        def body(carry, t):
            buf, inflight = carry
            mb = jnp.clip(t - idx, 0, M - 1)
            x_in = jnp.where(idx == 0, x_stack[jnp.clip(t, 0, M - 1)], inflight)
            y = stage_fn(x_in)
            active = jnp.logical_and(t - idx >= 0, t - idx < M)
            buf = jnp.where(active & (idx == n - 1),
                            buf.at[mb].set(y), buf)
            perm = [(i, (i + 1) % n) for i in range(n)]
            inflight = lax.ppermute(y, axis_name, perm)
            return (buf, inflight), None

        inflight0 = jnp.zeros_like(stage_fn(x_stack[0]))
        (buf, _), _ = lax.scan(body, (buf, inflight0), jnp.arange(steps))
        return buf
    return run
