"""ZeRO-style cross-replica sharded weight update: fusion buckets +
reduce-scatter/all-gather collectives (arXiv:2004.13336).

The replicated data-parallel step all-reduces every gradient and runs the
full optimizer update on every replica — N identical updates over N copies
of the optimizer state. Xu et al. (arXiv:2004.13336) observed that the
update decomposes: reduce-scatter the gradients so each replica owns 1/N of
them, update only that shard (with only that shard's optimizer state), and
all-gather the updated weights back. Wire bytes stay ~the all-reduce's
(reduce-scatter + all-gather IS how XLA lowers a ring all-reduce), but the
update compute and the optimizer-state memory both shrink by ~1/N.

This module holds the pieces `DataParallelTrainer(zero_update=True)` and the
kvstore's bucketed ``pushpull`` share:

  - a **bucket planner**: parameters are greedily packed, in declaration
    order, into dtype-homogeneous flat fusion buckets capped at
    ``MXNET_TPU_BUCKET_BYTES`` so small tensors amortize collective latency
    (the reference's kvstore big-array batching, inverted);
  - **flatten / unflatten / shard** helpers used inside the traced step;
  - the **reduce-scatter** itself, optionally compressed on the wire
    (``MXNET_TPU_COMM_DTYPE``): bf16, or EQuARX-style (arXiv:2506.17615)
    chunk-scaled int8 with fp32 accumulation of the scatter result;
  - wire-byte estimators feeding telemetry's per-kind collective counters.
"""
from __future__ import annotations

import bisect
import functools
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

import jax
from jax import lax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError, env
from .. import engine as _engine

__all__ = ["BucketSpec", "plan_buckets", "flatten_bucket", "unflatten_bucket",
           "shard_slice", "wd_vector", "reduce_scatter_bucket",
           "all_gather_bucket", "reduce_scatter_wire_bytes",
           "all_gather_wire_bytes", "per_replica_state_bytes",
           "canonical_comm_dtype", "shard_map_compat"]

env.declare("MXNET_TPU_ZERO", False, bool,
            "Default DataParallelTrainer(zero_update=...) to the ZeRO-style "
            "sharded weight update (reduce-scatter + 1/N update + all-gather)")
env.declare("MXNET_TPU_BUCKET_BYTES", 32 * 1024 * 1024, int,
            "Size cap per gradient fusion bucket in the sharded update / "
            "bucketed kvstore pushpull (bytes of the bucket dtype)")
env.declare("MXNET_TPU_COMM_DTYPE", "", str,
            "Wire dtype for the sharded-update reduce-scatter: '' (native), "
            "'bfloat16', or 'int8' (chunk-scaled, fp32 accumulation)")


def canonical_comm_dtype(dtype) -> Optional[str]:
    """Normalize a comm-dtype spec to None | 'bfloat16' | 'int8'."""
    if dtype is None:
        return None
    name = str(jnp.dtype(dtype).name) if not isinstance(dtype, str) else dtype
    name = name.strip().lower()
    if name in ("", "none", "float32", "fp32"):
        return None
    if name in ("bfloat16", "bf16"):
        return "bfloat16"
    if name == "int8":
        return "int8"
    raise MXNetError(
        f"unsupported comm dtype {dtype!r}; use 'bfloat16' or 'int8' "
        "(MXNET_TPU_COMM_DTYPE)")


@dataclass(frozen=True)
class BucketSpec:
    """One flat fusion bucket: which parameter slots it packs and where.

    ``padded_size`` is a multiple of ``ndp`` so the bucket reduce-scatters
    into ``ndp`` equal contiguous shards; the tail pad stays zero through
    the update (zero grad, zero wd — see ``wd_vector``)."""
    dtype: str
    indices: Tuple[int, ...]
    offsets: Tuple[int, ...]
    sizes: Tuple[int, ...]
    shapes: Tuple[Tuple[int, ...], ...]
    padded_size: int
    ndp: int

    @property
    def used_size(self) -> int:
        return self.offsets[-1] + self.sizes[-1]

    @property
    def pad(self) -> int:
        return self.padded_size - self.used_size

    @property
    def shard_size(self) -> int:
        return self.padded_size // self.ndp

    @property
    def nbytes(self) -> int:
        return self.padded_size * jnp.dtype(self.dtype).itemsize


def plan_buckets(entries: Sequence[Tuple[int, Sequence[int], Any]],
                 ndp: int, bucket_bytes: int,
                 boundaries: Optional[Sequence[int]] = None
                 ) -> Tuple[BucketSpec, ...]:
    """Pack ``(slot_index, shape, dtype)`` entries into dtype-homogeneous
    buckets, greedily in order, size-capped at ``bucket_bytes`` (a tensor
    larger than the cap gets a bucket of its own). Every bucket is padded to
    a multiple of ``ndp`` elements.

    ``boundaries`` is an optional increasing sequence of slot indices at
    which a bucket must close: no bucket packs two entries that fall on
    opposite sides of a boundary (entry ``i`` belongs to side
    ``bisect_right(boundaries, i)``). The backward-overlap path
    (parallel/overlap.py) aligns buckets to its vjp segments this way, so
    every bucket's collective can be issued the moment one segment's
    backward finalizes. ``boundaries=None`` (or empty) produces plans
    byte-identical to the unhinted planner — the kvstore's bucketed
    ``pushpull`` relies on that."""
    ndp = max(int(ndp), 1)
    bounds = tuple(sorted(int(b) for b in boundaries)) if boundaries else ()
    groups: List[Tuple[str, List[Tuple[int, Tuple[int, ...], int]]]] = []
    by_dtype = {}
    for idx, shape, dtype in entries:
        key = str(jnp.dtype(dtype))
        if key not in by_dtype:
            by_dtype[key] = []
            groups.append((key, by_dtype[key]))
        shape = tuple(int(d) for d in shape)
        size = 1
        for d in shape:
            size *= d
        by_dtype[key].append((idx, shape, size))

    buckets: List[BucketSpec] = []

    def close(dtype, members):
        if not members:
            return
        offsets, off = [], 0
        for _, _, size in members:
            offsets.append(off)
            off += size
        padded = -(-off // ndp) * ndp
        buckets.append(BucketSpec(
            dtype=dtype,
            indices=tuple(i for i, _, _ in members),
            offsets=tuple(offsets),
            sizes=tuple(s for _, _, s in members),
            shapes=tuple(shp for _, shp, _ in members),
            padded_size=padded, ndp=ndp))

    for dtype, members in groups:
        cap = max(int(bucket_bytes) // jnp.dtype(dtype).itemsize, 1)
        cur, total, side = [], 0, None
        for idx, shape, size in members:
            s = bisect.bisect_right(bounds, idx) if bounds else 0
            if cur and (total + size > cap or s != side):
                close(dtype, cur)
                cur, total = [], 0
            cur.append((idx, shape, size))
            total += size
            side = s
        close(dtype, cur)
    return tuple(buckets)


def flatten_bucket(bucket: BucketSpec, arrays) -> jnp.ndarray:
    """Concatenate the bucket's slots of ``arrays`` (indexed by
    ``bucket.indices``) into one flat padded vector."""
    parts = [jnp.reshape(arrays[i], (-1,)) for i in bucket.indices]
    if bucket.pad:
        parts.append(jnp.zeros((bucket.pad,), parts[0].dtype))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def unflatten_bucket(bucket: BucketSpec, flat):
    """Inverse of ``flatten_bucket``: yields ``(slot_index, array)`` views
    reshaped back to each parameter's shape (the pad is dropped)."""
    return [(i, jnp.reshape(flat[o:o + s], shp))
            for i, o, s, shp in zip(bucket.indices, bucket.offsets,
                                    bucket.sizes, bucket.shapes)]


def shard_slice(bucket: BucketSpec, flat, position):
    """This replica's contiguous 1/ndp shard of a flat bucket; ``position``
    is the (traced) index along the dp axis."""
    return lax.dynamic_slice_in_dim(
        flat, position * bucket.shard_size, bucket.shard_size)


def wd_vector(bucket: BucketSpec, wds) -> _np.ndarray:
    """Per-element weight-decay vector for a bucket (the flat shard spans
    parameters with different wd; the update kernels broadcast it
    elementwise). The pad region gets wd=0 so padded weights stay zero."""
    out = _np.zeros((bucket.padded_size,), _np.float32)
    for i, o, s in zip(bucket.indices, bucket.offsets, bucket.sizes):
        out[o:o + s] = float(wds[i])
    return out


# ---------------------------------------------------------------------------
# Collectives (called inside the traced step, under shard_map over dp)
# ---------------------------------------------------------------------------

def shard_map_compat(body, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions: top-level (check_vma) on new
    releases, ``jax.experimental.shard_map`` (check_rep) before that."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    from jax.experimental.shard_map import shard_map as sm_exp
    return sm_exp(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)

def reduce_scatter_bucket(flat, axis_name: str, ndp: int,
                          comm_dtype: Optional[str] = None):
    """Cross-replica reduce-scatter of one flat bucket: returns this
    replica's 1/ndp shard of the SUM, as float32.

    comm_dtype None: native ``lax.psum_scatter`` (XLA schedules the ring).
    'bfloat16': the wire carries bf16 chunks (half the bytes); the scatter
    is realized as all_to_all + local sum so ACCUMULATION stays fp32.
    'int8': EQuARX-style chunk-scaled quantization — each (replica, shard)
    tile ships as int8 plus one fp32 scale (max/127), and the dequantized
    tiles are summed in fp32."""
    if ndp <= 1:
        return flat.astype(jnp.float32)
    if comm_dtype is None:
        return lax.psum_scatter(flat, axis_name, scatter_dimension=0,
                                tiled=True).astype(jnp.float32)
    chunks = jnp.reshape(flat, (ndp, -1))
    if comm_dtype == "bfloat16":
        recv = lax.all_to_all(chunks.astype(jnp.bfloat16), axis_name,
                              split_axis=0, concat_axis=0, tiled=True)
        return jnp.sum(recv.astype(jnp.float32), axis=0)
    if comm_dtype == "int8":
        chunks = chunks.astype(jnp.float32)
        amax = jnp.max(jnp.abs(chunks), axis=1, keepdims=True)
        scale = jnp.where(amax > 0, amax / 127.0, jnp.float32(1.0))
        q = jnp.clip(jnp.round(chunks / scale), -127, 127).astype(jnp.int8)
        recv = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)
        rscale = lax.all_to_all(scale, axis_name, split_axis=0,
                                concat_axis=0, tiled=True)
        return jnp.sum(recv.astype(jnp.float32) * rscale, axis=0)
    raise MXNetError(f"unsupported comm dtype {comm_dtype!r}")


def all_gather_bucket(shard, axis_name: str):
    """Gather every replica's updated shard back into the full flat bucket
    (XLA overlaps this with the next forward when it can)."""
    return lax.all_gather(shard, axis_name, axis=0, tiled=True)


# ---------------------------------------------------------------------------
# Accounting (telemetry estimates; ring schedule, like _grad_allreduce_bytes)
# ---------------------------------------------------------------------------

def reduce_scatter_wire_bytes(buckets, ndp: int,
                              comm_dtype: Optional[str] = None) -> int:
    """Per-step wire bytes of the bucket reduce-scatters: each replica
    sends (n-1)/n of every bucket once (plus the int8 path's scales)."""
    if ndp <= 1:
        return 0
    total = 0
    for b in buckets:
        itemsize = jnp.dtype(comm_dtype or b.dtype).itemsize
        nbytes = b.padded_size * itemsize
        if comm_dtype == "int8":
            nbytes += b.ndp * 4  # one fp32 scale per (replica, shard) tile
        total += nbytes * (ndp - 1) // ndp
    return total


def all_gather_wire_bytes(buckets, ndp: int) -> int:
    """Per-step wire bytes of gathering the updated shards (always the
    weight dtype — quantizing the weights themselves would bias training)."""
    if ndp <= 1:
        return 0
    return sum(b.padded_size * jnp.dtype(b.dtype).itemsize * (ndp - 1) // ndp
               for b in buckets)


def per_replica_state_bytes(tree) -> int:
    """Bytes of optimizer state ONE replica actually holds: dp-sharded
    leaves count their local shard only, replicated leaves their full size
    (feeds the mx_optimizer_state_per_replica_bytes gauge)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            try:
                shape = sharding.shard_shape(tuple(shape))
            except Exception:
                pass
        elems = 1
        for d in shape:
            elems *= int(d)
        total += elems * jnp.dtype(dtype).itemsize
    return total


# ---------------------------------------------------------------------------
# Eager sharded-update kernels (kvstore / host-driven paths)
# ---------------------------------------------------------------------------

def _sharded_update_kernel(*donate):
    """``optimizer._update_kernel``'s analog for flat fusion buckets: jit
    the kernel donating the given argnums, so a reduce-scattered bucket
    (and any optimizer-state shard riding with it) aliases its output in
    place. mxlint's donation-safety pass knows this decorator — reading a
    donated bucket, or any view sliced out of it, after the call is
    flagged."""
    def wrap(fn):
        cache = {"jit": None}

        @functools.wraps(fn)
        def call(*args):
            if cache["jit"] is None:
                donating = bool(donate) and _engine.donation_enabled()
                cache["jit"] = jax.jit(
                    fn, donate_argnums=donate if donating else ())
            return cache["jit"](*args)
        call.__wrapped__ = fn
        return call
    return wrap


@_sharded_update_kernel(0)
def _k_bucket_reduce(stacked):
    """Sum a (contributors, bucket_size) stack of bucket gradients in fp32 —
    one fused XLA reduction for a whole bucket; the stack is dead afterwards
    and is donated."""
    return jnp.sum(stacked.astype(jnp.float32), axis=0)
