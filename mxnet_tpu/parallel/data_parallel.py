"""Fused data-parallel training step (the TPU path that replaces reference
SURVEY.md §3.5: Trainer.step → kvstore pushpull → Comm/NCCL/ps-lite).

One `jax.jit` computes forward + backward + allreduce + optimizer update:
batch enters sharded over the 'dp' mesh axis, parameters stay replicated (or
sharded per their Parameter.sharding spec for TP), and XLA inserts the grad
all-reduce over ICI. Weight update runs replicated, or sharded — ZeRO-style
(arXiv:2004.13336) — with ``zero_update=True``/``MXNET_TPU_ZERO=1``:
gradients flatten into fusion buckets (parallel/zero.py), reduce-scatter
over dp (optionally bf16/int8-compressed, ``MXNET_TPU_COMM_DTYPE``), each
replica updates its 1/N shard against 1/N of the optimizer state, and the
updated shards all-gather back into the replicated weights inside the same
jit so XLA can overlap the gather with the next forward.
"""
from __future__ import annotations

import functools
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
from jax import lax
import jax.numpy as jnp
import numpy as _np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..base import MXNetError, env
from ..ndarray import NDArray
from .. import autograd
from .. import engine as _engine
from ..engine import async_feed as _feed
from ..engine import xla_flags as _xla_flags
from .. import random as _rng
from .. import sanitize as _sanitize
from .. import telemetry as _telem
from ..telemetry import tracing as _tracing
from ..gluon.block import HybridBlock, _AUX_STACK
from ..gluon.parameter import Parameter
from .. import optimizer as opt_mod
from . import overlap as _overlap
from . import zero as _zero
from .mesh import current_mesh, P
from .step_program import StepProgram


# ---------------------------------------------------------------------------
# Functional adapters over the eager Optimizer kernels
# ---------------------------------------------------------------------------

def functional_optimizer(opt: "opt_mod.Optimizer"):
    """Return (init_state(w_tree)->s_tree, update(g,w,s,t)->(w,s)) for an
    Optimizer instance, reusing its update formulas."""
    from ..optimizer.optimizer import (SGD, NAG, Adam, AdamW, LAMB, LARS,
                                       RMSProp, AdaGrad, _k_sgd, _k_sgd_mom,
                                       _k_nag, _k_adam, _k_adamw, _k_lamb,
                                       _k_lars, _k_rmsprop, _k_adagrad)

    # UNWRAP the @jax.jit kernels: inside the fused train step each jitted
    # kernel traces as a closed pjit call, so ~160 per-param updates become
    # ~160 separate XLA computations per step that cannot fuse with each
    # other or the backward. Measured on ResNet-50 bs32 (chip): the true
    # SGD-momentum cost is 0.38 ms/step inlined vs ~5 ms through the
    # nested-jit calls (benchmark/opt_overhead_probe.py). The eager
    # Updater path still uses the jitted aliases directly.
    (_k_sgd, _k_sgd_mom, _k_nag, _k_adam, _k_adamw, _k_lamb, _k_lars,
     _k_rmsprop, _k_adagrad) = (
        getattr(k, "__wrapped__", k)
        for k in (_k_sgd, _k_sgd_mom, _k_nag, _k_adam, _k_adamw, _k_lamb,
                  _k_lars, _k_rmsprop, _k_adagrad))

    def _f(x):
        return jnp.float32(x)

    clip = opt.clip_gradient if opt.clip_gradient is not None else -1.0

    if isinstance(opt, AdamW):
        def init(w):
            return (jnp.zeros_like(w), jnp.zeros_like(w))

        def update(g, w, s, t, lr, wd):
            m, v = s
            c1 = 1 - opt.beta1 ** t
            c2 = 1 - opt.beta2 ** t
            w2, m2, v2 = _k_adamw(w, g, m, v, lr, _f(opt.eta), wd,
                                  _f(opt.rescale_grad), _f(clip), _f(opt.beta1),
                                  _f(opt.beta2), _f(opt.epsilon), c1, c2)
            return w2, (m2, v2)
        return init, update

    if isinstance(opt, LAMB):
        def init(w):
            return (jnp.zeros_like(w, dtype=jnp.float32),
                    jnp.zeros_like(w, dtype=jnp.float32))

        def update(g, w, s, t, lr, wd):
            m, v = s
            c1 = 1 - opt.beta1 ** t
            c2 = 1 - opt.beta2 ** t
            w2, m2, v2 = _k_lamb(w, g, m, v, lr, wd, _f(opt.rescale_grad),
                                 _f(clip), _f(opt.beta1), _f(opt.beta2),
                                 _f(opt.epsilon), c1, c2,
                                 _f(opt.lower_bound or 0.0),
                                 _f(opt.upper_bound or jnp.inf),
                                 jnp.bool_(opt.bias_correction))
            return w2, (m2, v2)
        return init, update

    if isinstance(opt, Adam):
        def init(w):
            return (jnp.zeros_like(w), jnp.zeros_like(w))

        def update(g, w, s, t, lr, wd):
            m, v = s
            c1 = 1 - opt.beta1 ** t
            c2 = 1 - opt.beta2 ** t
            w2, m2, v2 = _k_adam(w, g, m, v, lr, wd, _f(opt.rescale_grad),
                                 _f(clip), _f(opt.beta1), _f(opt.beta2),
                                 _f(opt.epsilon), c1, c2)
            return w2, (m2, v2)
        return init, update

    if isinstance(opt, LARS):
        def init(w):
            return jnp.zeros_like(w)

        def update(g, w, s, t, lr, wd):
            w2, s2 = _k_lars(w, g, s, lr, wd, _f(opt.rescale_grad), _f(clip),
                             _f(opt.momentum), _f(opt.eta), _f(opt.epsilon))
            return w2, s2
        return init, update

    if isinstance(opt, NAG):
        def init(w):
            return jnp.zeros_like(w)

        def update(g, w, s, t, lr, wd):
            w2, s2 = _k_nag(w, g, s, lr, wd, _f(opt.rescale_grad), _f(clip),
                            _f(opt.momentum))
            return w2, s2
        return init, update

    if isinstance(opt, RMSProp) and not opt.centered:
        def init(w):
            return jnp.zeros_like(w)

        def update(g, w, s, t, lr, wd):
            w2, s2 = _k_rmsprop(w, g, s, lr, wd, _f(opt.rescale_grad), _f(clip),
                                _f(opt.gamma1), _f(opt.epsilon))
            return w2, s2
        return init, update

    if isinstance(opt, AdaGrad):
        def init(w):
            return jnp.zeros_like(w)

        def update(g, w, s, t, lr, wd):
            w2, s2 = _k_adagrad(w, g, s, lr, wd, _f(opt.rescale_grad), _f(clip),
                                _f(opt.float_stable_eps))
            return w2, s2
        return init, update

    if isinstance(opt, SGD):
        mom = getattr(opt, "momentum", 0.0)
        if mom == 0.0:
            def init(w):
                return ()

            def update(g, w, s, t, lr, wd):
                return _k_sgd(w, g, lr, wd, _f(opt.rescale_grad), _f(clip)), ()
            return init, update

        def init(w):
            return jnp.zeros_like(w)

        def update(g, w, s, t, lr, wd):
            w2, s2 = _k_sgd_mom(w, g, s, lr, wd, _f(opt.rescale_grad), _f(clip),
                                _f(mom))
            return w2, s2
        return init, update

    raise MXNetError(f"no functional adapter for optimizer "
                     f"{type(opt).__name__}; use gluon.Trainer or add one")


def functional_lazy_update(opt: "opt_mod.Optimizer"):
    """Lazy (row-sparse) variant of the functional update — applied per
    parameter whose grad_stype is row_sparse (reference lazy_update
    semantics: untouched rows skip wd/momentum decay entirely). Returns
    None when the optimizer has no lazy form."""
    from ..optimizer.optimizer import (SGD, NAG, Adam, AdamW, LAMB,
                                       _k_sgd_lazy, _k_sgd_mom_lazy,
                                       _k_adam_lazy)

    # unwrap nested jits for the same fusion reason as functional_optimizer
    _k_sgd_lazy, _k_sgd_mom_lazy, _k_adam_lazy = (
        getattr(k, "__wrapped__", k)
        for k in (_k_sgd_lazy, _k_sgd_mom_lazy, _k_adam_lazy))

    if not getattr(opt, "lazy_update", False):
        return None

    def _f(x):
        return jnp.float32(x)

    clip = opt.clip_gradient if opt.clip_gradient is not None else -1.0

    if isinstance(opt, (AdamW, LAMB, NAG)):
        return None  # no lazy form in the reference either
    if isinstance(opt, Adam):
        def update(g, w, s, t, lr, wd):
            m, v = s
            c1 = 1 - opt.beta1 ** t
            c2 = 1 - opt.beta2 ** t
            w2, m2, v2 = _k_adam_lazy(w, g, m, v, lr, wd,
                                      _f(opt.rescale_grad), _f(clip),
                                      _f(opt.beta1), _f(opt.beta2),
                                      _f(opt.epsilon), c1, c2)
            return w2, (m2, v2)
        return update
    if isinstance(opt, SGD):  # includes LBSGD, which inherits SGD.update
        mom = getattr(opt, "momentum", 0.0)
        if mom == 0.0:
            def update(g, w, s, t, lr, wd):
                return _k_sgd_lazy(w, g, lr, wd, _f(opt.rescale_grad),
                                   _f(clip)), ()
            return update

        def update(g, w, s, t, lr, wd):
            w2, s2 = _k_sgd_mom_lazy(w, g, s, lr, wd, _f(opt.rescale_grad),
                                     _f(clip), _f(mom))
            return w2, s2
        return update
    return None


def _make_apply_fn(block: HybridBlock, plist: List[Parameter], train: bool,
                   aux_order_out: Optional[List[Parameter]] = None):
    """Pure fn(key_raw, params_raw_list, *inputs_raw) -> (outputs, aux_list).
    Same parameter-swap trick as HybridBlock's cached graph. When
    aux_order_out is given, the Parameters whose aux values the forward
    emits (BN running stats) are recorded there on the first call, in the
    same order as the returned aux_list."""
    def apply_fn(key_raw, params_raw, *raw_inputs):
        in_nds = [NDArray(r) for r in raw_inputs]
        saved = [p._data._data for p in plist]
        aux: List[Tuple[Parameter, Any]] = []
        _AUX_STACK.append(aux)
        from ..gluon.block import _TRACE_DEPTH
        _TRACE_DEPTH[0] += 1
        prev_rec = autograd.set_recording(False)
        prev_train = autograd.set_training(train)
        _rng.push_trace_key(key_raw)
        try:
            for p, r in zip(plist, params_raw):
                p._data._data = r
            out = block._forward_unhybridized(*in_nds)
        finally:
            _rng.pop_trace_key()
            for p, s in zip(plist, saved):
                p._data._data = s
            _AUX_STACK.pop()
            _TRACE_DEPTH[0] -= 1
            autograd.set_recording(prev_rec)
            autograd.set_training(prev_train)
        leaves = jax.tree_util.tree_leaves(
            out, is_leaf=lambda x: isinstance(x, NDArray))
        raw_out = [l._data if isinstance(l, NDArray) else l for l in leaves]
        if aux_order_out is not None and not aux_order_out:
            aux_order_out.extend(p for p, _ in aux)
        return raw_out[0] if len(raw_out) == 1 else tuple(raw_out), \
            [v for _, v in aux]
    return apply_fn


class DataParallelTrainer:
    """One-jit data-parallel trainer.

    net must be a HybridBlock already initialized; loss_fn(F-less) maps
    (pred_raw, label_raw) -> scalar raw loss, built from jax ops, OR pass a
    gluon Loss block.

    step(x, y) -> float loss. Parameters/optimizer state live on device as
    raw arrays between steps (donated — no host round-trip), synced back into
    the gluon Parameters on `sync()` / checkpoint.
    """

    def __init__(self, net: HybridBlock, loss, optimizer="sgd",
                 optimizer_params=None, mesh: Optional[Mesh] = None,
                 batch_axis_name: str = "dp", dtype=None, data_spec=None,
                 compression=None, zero_update=None, bucket_bytes=None,
                 comm_dtype=None, overlap_grads=None, overlap_segments=None):
        self.net = net
        # Mixed precision: dtype="bfloat16" (or "float16") runs forward/backward
        # in low precision with fp32 master weights + fp32 optimizer math —
        # the TPU-native analog of reference AMP (python/mxnet/contrib/amp/).
        self.compute_dtype = None
        if dtype is None:
            # amp.init() makes low-precision the session default
            try:
                from ..contrib.amp import amp as _amp
                dtype = _amp.target_dtype()
            except ImportError:
                pass
        if dtype is not None and jnp.dtype(dtype) != jnp.dtype(jnp.float32):
            self.compute_dtype = jnp.dtype(dtype)
            if self.compute_dtype not in (jnp.dtype(jnp.bfloat16),
                                          jnp.dtype(jnp.float16)):
                raise MXNetError(
                    "dtype must be float32/bfloat16/float16, got %r" % dtype)
        # fp16 needs dynamic loss scaling (grads under 2^-24 flush to zero);
        # bf16/f32 don't — scaler stays None and the step skips that logic
        self._scaler = None
        if self.compute_dtype == jnp.dtype(jnp.float16):
            from ..contrib.amp.loss_scaler import LossScaler
            self._scaler = LossScaler()
        self.mesh = mesh if mesh is not None else current_mesh()
        # computed once: the mesh never changes after construction, and the
        # per-step placement helpers sit on the hot path
        self._multiprocess = any(d.process_index != jax.process_index()
                                 for d in self.mesh.devices.flat)
        self.batch_axis = batch_axis_name
        # input PartitionSpec; default = batch over the dp axis only. Pass
        # e.g. P('dp', 'sp') to also shard the sequence dim (context parallel).
        self.data_spec = data_spec if data_spec is not None else P(batch_axis_name)
        self.optimizer = optimizer if isinstance(optimizer, opt_mod.Optimizer) \
            else opt_mod.create(optimizer, **(optimizer_params or {}))
        self._init_fn, self._update_fn = functional_optimizer(self.optimizer)
        self._lazy_update_fn = functional_lazy_update(self.optimizer)
        self.loss = loss
        deferred = [p.name for p in net.collect_params().values()
                    if p._data is None and p._deferred_init is not None]
        if deferred:
            raise MXNetError(
                "net has deferred-init parameters (%s…); run one eager "
                "forward pass before constructing DataParallelTrainer"
                % deferred[0])
        self._plist = [p for p in net.collect_params().values()
                       if p._data is not None]
        self._trainable = [p.grad_req != "null" for p in self._plist]
        self._lazy = [self._lazy_update_fn is not None and
                      getattr(p, "grad_stype", "default") == "row_sparse"
                      for p in self._plist]
        self._params_raw = [p._data._data for p in self._plist]
        self._t = 0
        # bounded in-flight dispatch (MXNET_TPU_INFLIGHT_STEPS): step()
        # returns without blocking and the window back-pressures on the
        # (i-K)th step's outputs — the reference dependency engine's
        # pending-op bound, realized over jax async dispatch
        self._window = _feed.DispatchWindow(name="dp")
        self._dp_degree = int(dict(self.mesh.shape).get(batch_axis_name, 1))
        self._ar_bytes: Optional[int] = None
        self._rs_bytes: Optional[int] = None   # zero: reduce-scatter wire
        self._ag_bytes: Optional[int] = None   # zero: all-gather wire
        self._opt_bytes: Optional[int] = None  # per-replica state footprint
        self._wds = [self.optimizer._get_wd(i)
                     for i in range(len(self._plist))]

        # ZeRO-style sharded weight update (arXiv:2004.13336; parallel/zero)
        if zero_update is None:
            zero_update = bool(env.get("MXNET_TPU_ZERO"))
        self._zero = bool(zero_update)
        self._bucket_bytes = int(bucket_bytes if bucket_bytes is not None
                                 else env.get("MXNET_TPU_BUCKET_BYTES"))
        # Backward-overlapped collectives (parallel/overlap.py): chunk the
        # backward into vjp segments and issue each segment-aligned bucket's
        # collective as the segment finalizes. Env-derived enablement
        # degrades to the plain step on unsegmentable nets (warning);
        # explicit overlap_grads=True raises instead.
        overlap_env = overlap_grads is None
        if overlap_grads is None:
            overlap_grads = bool(env.get("MXNET_TPU_OVERLAP_GRADS"))
        self._overlap = bool(overlap_grads)
        self._overlap_segments = int(
            overlap_segments if overlap_segments is not None
            else env.get("MXNET_TPU_OVERLAP_SEGMENTS"))
        self._overlap_plan = None
        self._overlap_buckets = ()
        if comm_dtype is None:
            comm_dtype = env.get("MXNET_TPU_COMM_DTYPE") or None
        self._comm_dtype = _zero.canonical_comm_dtype(comm_dtype) \
            if (self._zero or self._overlap) else None

        # shardings: params per their spec (default replicated)
        self._param_shardings = [
            NamedSharding(self.mesh, p.sharding if p.sharding is not None else P())
            for p in self._plist]
        self._params_raw = [self._place_param(w, s)
                            for w, s in zip(self._params_raw,
                                            self._param_shardings)]
        # resolve the overlap segmentation BEFORE any bucket planning: the
        # zero plan must align to segment boundaries so every bucket's
        # collective becomes issuable the moment one segment finalizes
        if self._overlap:
            try:
                self._validate_overlap(compression)
                self._overlap_plan = _overlap.plan_segments(
                    self.net, self._plist, self._overlap_segments)
                owning = sum(1 for s in self._overlap_plan.segments
                             if s.owned)
                if owning < 2:
                    raise MXNetError(
                        "overlap_grads needs >= 2 backward segments that "
                        f"own parameters, got {owning}; nothing to overlap")
            except MXNetError as e:
                if not overlap_env:
                    raise
                warnings.warn(
                    f"MXNET_TPU_OVERLAP_GRADS: falling back to the plain "
                    f"fused step ({e})", UserWarning, stacklevel=2)
                self._overlap = False
                self._overlap_plan = None
                if not self._zero:
                    self._comm_dtype = None
        if self._overlap:
            # async-collective / latency-hiding scheduler flags; a no-op
            # plus one per-process warning when the backend beat us to init
            _xla_flags.ensure_overlap_flags()
        # Optimizer state is created from the PLACED master weights, so each
        # leaf is born with its final placement (zeros_like inherits the
        # NamedSharding) — single-process included: the step jit requires
        # params and opt_state co-located, and net init under mx.cpu() on a
        # TPU-visible process otherwise leaves the state on the host. In
        # multi-controller SPMD this doubles as the global-array lift
        # (identical-per-process seeded state, the reference's rank-0
        # broadcast contract). Zero mode instead shards the state 1/dp over
        # flat fusion buckets.
        if self._zero:
            self._validate_zero(compression)
            self._init_zero_state()
        else:
            self._zero_plan = ()
            self._opt_state = [self._init_fn(w) if t else ()
                               for w, t in zip(self._params_raw,
                                               self._trainable)]
            if self._overlap:
                # non-zero overlap: segment-aligned fusion buckets carry the
                # per-bucket all-reduces (state stays replicated per param)
                entries = [(i, w.shape, w.dtype)
                           for i, (w, t) in enumerate(zip(self._params_raw,
                                                          self._trainable))
                           if t and jnp.issubdtype(w.dtype, jnp.floating)]
                self._overlap_buckets = _zero.plan_buckets(
                    entries, self._dp_degree, self._bucket_bytes,
                    boundaries=self._overlap_plan.boundaries)

        # 2-bit gradient compression with per-device error feedback
        # (reference src/kvstore/gradient_compression.cc:60). Each device
        # quantizes its LOCAL gradient (+ residual) to {-thr, 0, +thr}
        # before the cross-dp reduce — the collective then carries the
        # quantized tensor, like the reference's ps-lite push path. Needs
        # explicit per-device semantics, so the compressed step runs the
        # grad computation under shard_map over the dp axis; that is only
        # well-defined for pure data parallelism (replicated params,
        # batch-only data sharding), matching the reference's dist-DP scope.
        self._compression = dict(compression) if compression else None
        if self._compression:
            ctype = self._compression.get("type", "2bit")
            if ctype != "2bit":
                raise MXNetError(f"unsupported gradient compression {ctype!r}")
            bad = [p.name for p, s in zip(self._plist, self._param_shardings)
                   if any(ax is not None for ax in s.spec)]
            if bad or tuple(self.data_spec) != (self.batch_axis,):
                raise MXNetError(
                    "gradient compression requires pure data parallelism "
                    "(replicated parameters, data sharded over the batch "
                    f"axis only); offending params={bad[:3]} "
                    f"data_spec={self.data_spec}")
            sparse = [p.name for p, lz in zip(self._plist, self._lazy) if lz]
            if sparse:
                # a {-t,0,+t}-quantized gradient has no meaningful 'absent
                # rows' — lazy semantics would silently change under
                # compression (the reference also restricts compression to
                # dense gradients, src/kvstore/kvstore_dist.h)
                raise MXNetError(
                    "gradient compression is incompatible with row_sparse "
                    f"lazy-update parameters ({sparse[:3]}); use dense "
                    "gradients or disable compression")
            ndp = self.mesh.shape[self.batch_axis]
            thr_sh = NamedSharding(self.mesh, P(self.batch_axis))

            def _zeros_on(shape, sharding):
                # zeros are servable from every process: placement works on
                # multi-host meshes where device_put cannot reach
                # non-addressable devices
                if not self._multiprocess:
                    return jax.device_put(jnp.zeros(shape, jnp.float32),
                                          sharding)
                def _shard_zeros(idx, _s=shape):
                    dims = [len(range(*sl.indices(dim)))
                            for sl, dim in zip(idx, _s)]
                    return _np.zeros(tuple(dims), _np.float32)
                return jax.make_array_from_callback(shape, sharding,
                                                    _shard_zeros)

            self._comp_resid = [
                _zeros_on((ndp,) + w.shape, thr_sh)
                if t and jnp.issubdtype(w.dtype, jnp.floating) else
                _zeros_on((ndp, 1), thr_sh)
                for w, t in zip(self._params_raw, self._trainable)]
        else:
            self._comp_resid = []

        # process-wide engine-cache key base: N trainers over one model
        # structure and configuration share compiled step artifacts, while
        # any change to the zero/bucket/comm-dtype (or precision, mesh,
        # optimizer, compression) configuration compiles apart
        # (docs/compilation.md "fused-step fingerprints")
        self._step_key_base = (
            "dp_step",
            _engine.structural_fingerprint(net),
            _engine.config_fingerprint(
                optimizer=type(self.optimizer).__name__,
                opt_conf=tuple(sorted(
                    (k, repr(v)) for k, v in vars(self.optimizer).items()
                    if isinstance(v, (int, float, bool, str, type(None))))),
                wds=tuple(float(w) for w in self._wds),
                loss=self.loss,
                mesh=tuple(sorted(dict(self.mesh.shape).items())),
                axis_order=tuple(self.mesh.axis_names),
                devices=tuple(int(d.id) for d in self.mesh.devices.flat),
                batch_axis=self.batch_axis,
                data_spec=tuple(str(a) for a in self.data_spec),
                param_specs=tuple(str(s.spec) for s in self._param_shardings),
                trainable=tuple(self._trainable),
                lazy=tuple(self._lazy),
                compute_dtype=str(self.compute_dtype),
                scaled=self._scaler is not None,
                compression=tuple(sorted(self._compression.items()))
                if self._compression else None,
                zero=self._zero,
                bucket_bytes=self._bucket_bytes
                if (self._zero or self._overlap) else None,
                comm_dtype=self._comm_dtype,
                overlap=self._overlap_plan.fingerprint
                if self._overlap else None))
        # executables, cost captures and roofline regions live in the
        # PROCESS-WIDE engine cache behind this program (parallel/
        # step_program.py) — same-config trainers share compiles
        self._program = StepProgram(
            f"dp.step[{type(self.net).__name__}]", self._step_key_base)

    # -- ZeRO-style sharded update setup ------------------------------------
    def _validate_zero(self, compression):
        """zero_update preconditions: the flat-shard update is only defined
        for pure data parallelism with dense gradients and an elementwise
        optimizer."""
        if compression:
            raise MXNetError(
                "zero_update is incompatible with 2-bit gradient "
                "compression; use comm_dtype='bfloat16'/'int8' for "
                "compressed collectives instead")
        bad = [p.name for p, s in zip(self._plist, self._param_shardings)
               if any(ax is not None for ax in s.spec)]
        if bad or tuple(self.data_spec) != (self.batch_axis,):
            raise MXNetError(
                "zero_update requires pure data parallelism (replicated "
                "parameters, data sharded over the batch axis only); "
                f"offending params={bad[:3]} data_spec={self.data_spec}")
        sparse = [p.name for p, lz in zip(self._plist, self._lazy) if lz]
        if sparse:
            raise MXNetError(
                "zero_update is incompatible with row_sparse lazy-update "
                f"parameters ({sparse[:3]}): absent rows have no meaning "
                "inside a flattened bucket shard")
        from ..optimizer.optimizer import LAMB, LARS
        if isinstance(self.optimizer, (LAMB, LARS)):
            raise MXNetError(
                f"zero_update does not support "
                f"{type(self.optimizer).__name__}: its per-tensor "
                "trust-ratio norms do not decompose over flat bucket "
                "shards; use sgd/adam/adamw/...")

    def _validate_overlap(self, compression):
        """overlap_grads preconditions: the chunked-vjp backward with
        per-bucket collectives is only defined for pure data parallelism
        with dense gradients (zero_update's scope); 2-bit compression's
        per-parameter error-feedback carry has no segmented form."""
        if compression:
            raise MXNetError(
                "overlap_grads is incompatible with 2-bit gradient "
                "compression; use comm_dtype='bfloat16'/'int8' for a "
                "compressed overlapped wire instead")
        bad = [p.name for p, s in zip(self._plist, self._param_shardings)
               if any(ax is not None for ax in s.spec)]
        if bad or tuple(self.data_spec) != (self.batch_axis,):
            raise MXNetError(
                "overlap_grads requires pure data parallelism (replicated "
                "parameters, data sharded over the batch axis only); "
                f"offending params={bad[:3]} data_spec={self.data_spec}")
        sparse = [p.name for p, lz in zip(self._plist, self._lazy) if lz]
        if sparse:
            raise MXNetError(
                "overlap_grads is incompatible with row_sparse lazy-update "
                f"parameters ({sparse[:3]}): absent rows have no meaning "
                "inside a flattened bucket")

    def _init_zero_state(self):
        """Plan fusion buckets over the trainable master weights and create
        the optimizer state SHARDED: every bucket-state leaf lives under a
        per-shard NamedSharding over the dp axis, so each replica holds
        ~1/dp of the optimizer footprint (the
        mx_optimizer_state_per_replica_bytes gauge reports it). The
        per-bucket carry is (wd_vector, state_tree); the per-element wd
        vector rides the carry — sharded and donated through the step —
        instead of being baked into the trace as a full-size constant."""
        dp_sh = NamedSharding(self.mesh, P(self.batch_axis))
        entries = [(i, w.shape, w.dtype)
                   for i, (w, t) in enumerate(zip(self._params_raw,
                                                  self._trainable))
                   if t and jnp.issubdtype(w.dtype, jnp.floating)]
        self._zero_plan = _zero.plan_buckets(
            entries, self._dp_degree, self._bucket_bytes,
            boundaries=self._overlap_plan.boundaries
            if self._overlap else None)
        in_bucket = frozenset(i for b in self._zero_plan for i in b.indices)
        carry = []
        for b in self._zero_plan:
            flat_w = _zero.flatten_bucket(b, self._params_raw)
            state = opt_mod.init_functional_state(self._init_fn, flat_w,
                                                  sharding=dp_sh)
            wd_dev = self._put_replicated(_zero.wd_vector(b, self._wds),
                                          dp_sh)
            carry.append((wd_dev, state))
        extra = tuple(self._init_fn(w) if (t and i not in in_bucket) else ()
                      for i, (w, t) in enumerate(zip(self._params_raw,
                                                     self._trainable)))
        self._opt_state = (tuple(carry), extra)

    # -- multi-process placement --------------------------------------------
    def _is_multiprocess(self):
        return self._multiprocess

    def _put_replicated(self, arr, sharding):
        """Place a host value onto a (possibly multi-host) sharding. With a
        mesh spanning processes, jax.device_put cannot target non-addressable
        devices — build the global array from per-shard callbacks instead
        (every process holds the full value, so any index is servable)."""
        if not self._is_multiprocess():
            return jax.device_put(arr, sharding)
        host = _np.asarray(arr)
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx])

    def _place_param(self, w, sharding):
        """Donation-safe master-weight placement. The step jit donates these
        buffers, so the gluon Parameter's own array must never alias them.
        A host (numpy) value — or, multi-process, any value: the feed goes
        through a host round-trip — lands in fresh device buffers, as does
        a jax.Array resident on devices DISJOINT from the target mesh; no
        defensive copy needed for those (the old unconditional
        ``jnp.array(copy=True)`` round-tripped every parameter through an
        extra full copy at construction). An array already living on ANY
        target device does need the copy first: device_put passes a
        same-sharding array through as-is, and even a resharding
        device_put shares the overlapping device's shard buffer with its
        output — donating the placed array would then delete the
        Parameter's own buffer (tests/test_zero_dp.py regression)."""
        if not self._is_multiprocess() and isinstance(w, jax.Array):
            cur = getattr(w, "sharding", None)
            if cur is not None and \
                    set(cur.device_set) & set(sharding.device_set):
                w = jnp.array(w, copy=True)
        return self._put_replicated(w, sharding)

    def _put_batch(self, arr, sharding):
        """Batch input: in multi-process SPMD each process passes its LOCAL
        shard of the global batch (reference dist-DP feeds per-worker
        partitions); single-process passes the global batch.

        Skip the device_put when the array is already placed compatibly:
        through the tunneled TPU backend even a NO-OP device_put of a
        bs32 ResNet batch costs ~90 ms (it round-trips the buffer), which
        at run_steps(n=20) was ~4.5 ms/step of pure re-upload — the
        entire 'trainer machinery' gap of benchmark/opt_overhead_probe2.py.
        A 1-device NamedSharding is satisfied by any single-device array
        on that device; otherwise require an exactly-equivalent sharding."""
        if not self._is_multiprocess():
            if isinstance(arr, jax.Array):
                cur = arr.sharding
                dev = set(cur.device_set)
                want = set(sharding.device_set)
                if dev == want and (
                        len(want) == 1
                        or cur.is_equivalent_to(sharding, arr.ndim)):
                    return arr
            return jax.device_put(arr, sharding)
        # multi-host feed: make_array_from_process_local_data requires the
        # per-process batch shard as host numpy — a protocol boundary, not
        # a stray sync
        host = _np.asarray(arr)  # mxlint: disable=host-sync
        return jax.make_array_from_process_local_data(sharding, host)

    # -- telemetry -----------------------------------------------------------
    def _grad_allreduce_bytes(self) -> int:
        """Wire bytes of the per-step gradient all-reduce over the dp axis
        (ring estimate: 2*(n-1)/n of the trainable-param footprint)."""
        if self._ar_bytes is None:
            n = self._dp_degree
            total = sum(int(w.nbytes) for w, t in
                        zip(self._params_raw, self._trainable) if t)
            self._ar_bytes = (total * 2 * (n - 1)) // n if n > 1 else 0
        return self._ar_bytes

    def _record_zero_telemetry(self, steps):
        """Zero-mode collective accounting: distinct per-kind counters
        (reduce_scatter of the gradient buckets, all_gather of the updated
        shards — ring estimates over the fusion-bucket plan)."""
        if self._rs_bytes is None:
            self._rs_bytes = _zero.reduce_scatter_wire_bytes(
                self._zero_plan, self._dp_degree, self._comm_dtype)
            self._ag_bytes = _zero.all_gather_wire_bytes(
                self._zero_plan, self._dp_degree)
        nb = len(self._zero_plan)
        _telem.record_comm("reduce_scatter", self._rs_bytes * steps,
                           store="mesh", calls=steps * nb, axis="dp")
        _telem.record_comm("all_gather", self._ag_bytes * steps,
                           store="mesh", calls=steps * nb, axis="dp")

    def _record_overlap_telemetry(self, steps):
        """Overlap-mode collective accounting: the per-bucket collectives
        issued inside the backward book with the overlap='1' label —
        reduce-scatter of the gradient buckets under zero_update, the
        per-bucket all-reduce otherwise. Zero's all-gather of the updated
        shards runs at the tail, after the backward is gone, so it stays
        unoverlapped; the mx_comm_overlap_ratio gauge reports the split."""
        if self._rs_bytes is None:
            if self._zero:
                self._rs_bytes = _zero.reduce_scatter_wire_bytes(
                    self._zero_plan, self._dp_degree, self._comm_dtype)
                self._ag_bytes = _zero.all_gather_wire_bytes(
                    self._zero_plan, self._dp_degree)
            else:
                self._rs_bytes = _overlap.allreduce_wire_bytes(
                    self._overlap_buckets, self._dp_degree,
                    self._comm_dtype)
                self._ag_bytes = 0
        if self._zero:
            nb = len(self._zero_plan)
            _telem.record_comm("reduce_scatter", self._rs_bytes * steps,
                               store="mesh", calls=steps * nb,
                               overlapped=True, axis="dp")
            _telem.record_comm("all_gather", self._ag_bytes * steps,
                               store="mesh", calls=steps * nb, axis="dp")
        else:
            nb = len(self._overlap_buckets)
            _telem.record_comm("allreduce", self._rs_bytes * steps,
                               store="mesh", calls=steps * nb,
                               overlapped=True, axis="dp")

    def _opt_state_replica_bytes(self) -> int:
        if self._opt_bytes is None:
            tree = self._opt_state
            if self._zero:
                # the wd vector riding each bucket carry is a hyperparameter
                # constant, not optimizer state — the gauge compares the
                # state footprint against the replicated trainer's
                carry, extra = self._opt_state
                tree = ([st for _, st in carry], extra)
            self._opt_bytes = _zero.per_replica_state_bytes(tree)
        return self._opt_bytes

    def _region_name(self, cost_key) -> str:
        """Roofline-ledger row key for this trainer's fused step artifact:
        a readable net-class prefix plus a digest of the full compile key
        (structural fingerprint + config_fingerprint + signature) — two
        configs that compile apart ledger apart, N same-config trainers
        aggregate into one row (StepProgram.region)."""
        return self._program.region(cost_key)

    def _record_telemetry(self, sig, examples, steps, flops_key=None):
        cost_key = flops_key if flops_key is not None else sig
        cost = self._program.cost(cost_key)
        flops = cost.get("flops")
        if self._dp_degree > 1:
            if self._overlap:
                self._record_overlap_telemetry(steps)
            elif self._zero:
                self._record_zero_telemetry(steps)
            else:
                _telem.record_comm("allreduce",
                                   self._grad_allreduce_bytes() * steps,
                                   store="mesh", calls=steps, axis="dp")
        _telem.record_optimizer_state(self._opt_state_replica_bytes(),
                                      source="data_parallel")
        # roofline ledger + aggregate flops/bytes through the ONE engine
        # funnel (called after window admission: completion-paced, no sync)
        _engine.record_execution(
            "step", flops or 0.0,
            bytes_accessed=cost.get("bytes_accessed", 0.0),
            region=self._region_name(cost_key), steps=steps, cost=cost)
        _telem.record_step(examples, source="data_parallel", steps=steps,
                           flops_per_step=(flops / steps if flops else None),
                           lr=float(self.optimizer.learning_rate),
                           dispatch_wait_seconds=self._window.wait_seconds)

    # -- loss plumbing -------------------------------------------------------
    def _loss_raw(self, pred_raw, label_raw):
        from ..gluon.loss import Loss as GluonLoss
        if isinstance(self.loss, GluonLoss):
            out = self.loss._forward_unhybridized(NDArray(pred_raw), NDArray(label_raw))
            return jnp.mean(out._data)
        return jnp.mean(self.loss(pred_raw, label_raw))

    def _build_step(self, x_shape_dtype, y_shape_dtype):
        aux_order: List[Parameter] = []
        apply_fn = _make_apply_fn(self.net, self._plist, train=True,
                                  aux_order_out=aux_order)
        plist = self._plist
        update_fn = self._update_fn
        lazy_fn, lazy = self._lazy_update_fn, self._lazy
        loss_raw = self._loss_raw
        wds = [self.optimizer._get_wd(i) for i in range(len(self._plist))]
        trainable = self._trainable
        mesh = self.mesh
        batch_axis = self.batch_axis

        x_sh = NamedSharding(mesh, P(batch_axis))
        rep = NamedSharding(mesh, P())
        p_sh = self._param_shardings
        cdt = self.compute_dtype

        def _low(a):
            if cdt is not None and jnp.issubdtype(a.dtype, jnp.floating):
                return a.astype(cdt)
            return a

        # params/opt_state/x/y arrive pre-placed (device_put with NamedSharding);
        # XLA propagates shardings and inserts the dp all-reduce on grads.
        scaled = self._scaler is not None

        def step(params, opt_state, key, x, y, lr, t, loss_scale):
            def lossf(ps):
                # casting inside the differentiated fn keeps fp32 master
                # weights: astype's vjp casts the low-precision grads back
                out, aux = apply_fn(key, [_low(p) for p in ps], _low(x))
                pred = out if not isinstance(out, tuple) else out[0]
                lossv = loss_raw(pred, y)
                return lossv * loss_scale, (lossv, aux)
            (_, (lossv, aux)), grads = jax.value_and_grad(
                lossf, has_aux=True)(params)
            if scaled:
                inv = 1.0 / loss_scale
                grads = [g * inv if jnp.issubdtype(g.dtype, jnp.floating) else g
                         for g in grads]
                finite = jnp.bool_(True)
                for i, g in enumerate(grads):
                    if trainable[i] and jnp.issubdtype(g.dtype, jnp.floating):
                        finite = jnp.logical_and(
                            finite, jnp.all(jnp.isfinite(g.astype(jnp.float32))))
            else:
                finite = jnp.bool_(True)
            new_params, new_state = [], []
            for i, (g, w, s) in enumerate(zip(grads, params, opt_state)):
                if trainable[i]:
                    fn = lazy_fn if lazy[i] else update_fn
                    w2, s2 = fn(g, w, s, t, lr, jnp.float32(wds[i]))
                    w2 = w2.astype(w.dtype)
                    if scaled:  # skip the whole update on overflow
                        w2 = jnp.where(finite, w2, w)
                        s2 = jax.tree_util.tree_map(
                            lambda new, old: jnp.where(finite, new, old), s2, s)
                    new_params.append(w2)
                    new_state.append(s2)
                else:
                    new_params.append(w)
                    new_state.append(s)
            # BN running stats (aux) flow through the param carry so they
            # accumulate across steps and sync() sees them — non-trainable
            # params otherwise pass through untouched
            idx_of = {id(p): i for i, p in enumerate(plist)}
            for p, v in zip(aux_order, aux):
                j = idx_of.get(id(p))
                if j is not None and not trainable[j]:
                    new_params[j] = v.astype(new_params[j].dtype)
            return new_params, new_state, lossv, finite, aux
        return step

    def _build_step_compressed(self):
        """Fused step with 2-bit compression + error feedback before the
        cross-dp reduce (reference gradient_compression.cc semantics on the
        XLA collective path). Per-device gradients exist only under explicit
        SPMD, so the whole step body runs in shard_map over the dp axis."""
        aux_order: List[Parameter] = []
        apply_fn = _make_apply_fn(self.net, self._plist, train=True,
                                  aux_order_out=aux_order)
        plist = self._plist
        update_fn = self._update_fn
        loss_raw = self._loss_raw
        wds = [self.optimizer._get_wd(i) for i in range(len(self._plist))]
        trainable = self._trainable
        mesh = self.mesh
        ax = self.batch_axis
        thr = jnp.float32(self._compression.get("threshold", 0.5))
        cdt = self.compute_dtype
        scaled = self._scaler is not None

        def _low(a):
            if cdt is not None and jnp.issubdtype(a.dtype, jnp.floating):
                return a.astype(cdt)
            return a

        def body(params, opt_state, resid, key, x, y, lr, t, loss_scale):
            # x/y/resid are the device-local tiles; params are replicated
            idx = lax.axis_index(ax)
            kk = jax.random.wrap_key_data(key.astype(jnp.uint32),
                                          impl="threefry2x32")
            key_local = jax.random.key_data(jax.random.fold_in(kk, idx))

            def lossf(ps):
                out, aux = apply_fn(key_local, [_low(p) for p in ps], _low(x))
                pred = out if not isinstance(out, tuple) else out[0]
                lossv = loss_raw(pred, y)  # mean over the LOCAL batch
                return lossv * loss_scale, (lossv, aux)

            (_, (lossv, aux)), grads = jax.value_and_grad(
                lossf, has_aux=True)(params)
            if scaled:
                inv = 1.0 / loss_scale
                grads = [g * inv if jnp.issubdtype(g.dtype, jnp.floating)
                         else g for g in grads]
                fin = jnp.bool_(True)
                for i, g in enumerate(grads):
                    if trainable[i] and jnp.issubdtype(g.dtype, jnp.floating):
                        fin = jnp.logical_and(
                            fin, jnp.all(jnp.isfinite(g.astype(jnp.float32))))
                finite = lax.pmin(fin.astype(jnp.int32), ax).astype(jnp.bool_)
            else:
                finite = jnp.bool_(True)

            new_params, new_state, new_resid = [], [], []
            for i, (g, w, s, r) in enumerate(
                    zip(grads, params, opt_state, resid)):
                if not trainable[i]:
                    new_params.append(w)
                    new_state.append(s)
                    new_resid.append(r)
                    continue
                if jnp.issubdtype(w.dtype, jnp.floating):
                    # quantize LOCAL grad + residual to {-thr, 0, +thr};
                    # only the 2-bit tensor rides the collective
                    acc = g.astype(jnp.float32) + r[0]
                    q = jnp.where(acc >= thr, thr,
                                  jnp.where(acc <= -thr, -thr,
                                            jnp.zeros_like(acc)))
                    if scaled:
                        # an overflow step must not poison the error-feedback
                        # carry: NaN acc would make q == 0 forever after
                        new_resid.append(jnp.where(finite, acc - q, r[0])[None])
                    else:
                        new_resid.append((acc - q)[None])
                    gg = lax.pmean(q, ax)
                else:
                    new_resid.append(r)
                    gg = lax.pmean(g, ax)
                w2, s2 = update_fn(gg, w, s, t, lr, jnp.float32(wds[i]))
                w2 = w2.astype(w.dtype)
                if scaled:
                    w2 = jnp.where(finite, w2, w)
                    s2 = jax.tree_util.tree_map(
                        lambda new, old: jnp.where(finite, new, old), s2, s)
                new_params.append(w2)
                new_state.append(s2)
            glob_loss = lax.pmean(lossv, ax)
            aux = jax.tree_util.tree_map(
                lambda v: lax.pmean(v, ax)
                if jnp.issubdtype(v.dtype, jnp.floating) else v, aux)
            # cross-device-averaged BN running stats flow through the carry
            idx_of = {id(p): i for i, p in enumerate(plist)}
            for p, v in zip(aux_order, aux):
                j = idx_of.get(id(p))
                if j is not None and not trainable[j]:
                    new_params[j] = v.astype(new_params[j].dtype)
            return new_params, new_state, new_resid, glob_loss, finite, aux

        dp = P(ax)
        rep = P()
        return _zero.shard_map_compat(
            body, mesh=mesh,
            in_specs=(rep, rep, dp, rep, dp, dp, rep, rep, rep),
            out_specs=(rep, rep, dp, rep, rep, rep))

    def _build_step_zero(self):
        """Fused step with the ZeRO-style sharded weight update
        (arXiv:2004.13336): local gradients flatten into dtype-homogeneous
        fusion buckets, each bucket is reduce-scattered over the dp axis
        (optionally bf16/int8-compressed on the wire, EQuARX-style), every
        replica runs the functional optimizer on its contiguous 1/N shard
        against 1/N of the optimizer state, and the updated shards are
        all-gathered back into the replicated weights — one shard_map body
        inside the single jitted step, so XLA can overlap the all-gather
        with the next forward. Same call/return contract as _build_step."""
        aux_order: List[Parameter] = []
        apply_fn = _make_apply_fn(self.net, self._plist, train=True,
                                  aux_order_out=aux_order)
        plist = self._plist
        update_fn = self._update_fn
        loss_raw = self._loss_raw
        wds = self._wds
        trainable = self._trainable
        mesh = self.mesh
        ax = self.batch_axis
        ndp = self._dp_degree
        buckets = self._zero_plan
        in_bucket = frozenset(i for b in buckets for i in b.indices)
        comm = self._comm_dtype
        cdt = self.compute_dtype
        scaled = self._scaler is not None

        def _low(a):
            if cdt is not None and jnp.issubdtype(a.dtype, jnp.floating):
                return a.astype(cdt)
            return a

        def body(params, opt_state, key, x, y, lr, t, loss_scale):
            # x/y are the device-local batch tiles; params replicated
            bucket_carry, extra_state = opt_state
            pos = lax.axis_index(ax)
            kk = jax.random.wrap_key_data(key.astype(jnp.uint32),
                                          impl="threefry2x32")
            key_local = jax.random.key_data(jax.random.fold_in(kk, pos))

            def lossf(ps):
                out, aux = apply_fn(key_local, [_low(p) for p in ps], _low(x))
                pred = out if not isinstance(out, tuple) else out[0]
                lossv = loss_raw(pred, y)  # mean over the LOCAL batch
                return lossv * loss_scale, (lossv, aux)

            (_, (lossv, aux)), grads = jax.value_and_grad(
                lossf, has_aux=True)(params)
            if scaled:
                inv = 1.0 / loss_scale
                grads = [g * inv if jnp.issubdtype(g.dtype, jnp.floating)
                         else g for g in grads]
                fin = jnp.bool_(True)
                for i, g in enumerate(grads):
                    if trainable[i] and jnp.issubdtype(g.dtype, jnp.floating):
                        fin = jnp.logical_and(
                            fin, jnp.all(jnp.isfinite(g.astype(jnp.float32))))
                finite = lax.pmin(fin.astype(jnp.int32), ax).astype(jnp.bool_)
            else:
                finite = jnp.bool_(True)

            def _gate(new, old):
                # fp16 overflow step: keep the old buffer contents
                return jnp.where(finite, new, old) if scaled else new

            new_params = list(params)
            new_extra = list(extra_state)
            # trainables outside every bucket (non-float dtypes): replicated
            # update on the pmean'd gradient — the plain step's math
            for i, (g, w, s) in enumerate(zip(grads, params, extra_state)):
                if not trainable[i] or i in in_bucket:
                    continue
                gg = lax.pmean(g, ax)
                w2, s2 = update_fn(gg, w, s, t, lr, jnp.float32(wds[i]))
                new_params[i] = _gate(w2.astype(w.dtype), w)
                new_extra[i] = jax.tree_util.tree_map(_gate, s2, s) \
                    if scaled else s2
            # buckets: reduce-scatter -> 1/N sharded update -> all-gather
            new_carry = []
            for b, (wd_vec, st) in zip(buckets, bucket_carry):
                flat_g = _zero.flatten_bucket(b, grads)
                g_shard = _zero.reduce_scatter_bucket(flat_g, ax, ndp,
                                                      comm) / ndp
                w_shard = _zero.shard_slice(
                    b, _zero.flatten_bucket(b, params), pos)
                w2, s2 = update_fn(g_shard.astype(w_shard.dtype), w_shard,
                                   st, t, lr, wd_vec)
                w2 = _gate(w2.astype(w_shard.dtype), w_shard)
                s2 = jax.tree_util.tree_map(_gate, s2, st) if scaled else s2
                full = _zero.all_gather_bucket(w2, ax)
                for i, arr in _zero.unflatten_bucket(b, full):
                    new_params[i] = arr.astype(params[i].dtype)
                new_carry.append((wd_vec, s2))
            glob_loss = lax.pmean(lossv, ax)
            aux = jax.tree_util.tree_map(
                lambda v: lax.pmean(v, ax)
                if jnp.issubdtype(v.dtype, jnp.floating) else v, aux)
            # cross-device-averaged BN running stats flow through the carry
            idx_of = {id(p): i for i, p in enumerate(plist)}
            for p, v in zip(aux_order, aux):
                j = idx_of.get(id(p))
                if j is not None and not trainable[j]:
                    new_params[j] = v.astype(new_params[j].dtype)
            return (new_params, (tuple(new_carry), tuple(new_extra)),
                    glob_loss, finite, aux)

        dp = P(ax)
        rep = P()
        return _zero.shard_map_compat(
            body, mesh=mesh,
            in_specs=(rep, (P(ax), rep), rep, dp, dp, rep, rep, rep),
            out_specs=(rep, (P(ax), rep), rep, rep, rep))

    def _build_step_overlap(self):
        """Fused step with backward-overlapped gradient collectives
        (parallel/overlap.py): the forward runs as K chained ``jax.vjp``
        segments (the per-cell vjp machinery the 1F1B pipeline schedule
        proved out, applied along one replica's depth), the backward
        replays the pullbacks newest-first, and each segment-aligned fusion
        bucket's collective — reduce-scatter under zero_update, all-reduce
        otherwise, either comm dtype — issues the moment its owning
        segment's pullback finalizes, while the older segments' backward
        dots are still ahead of the scheduler (async-collective XLA flags:
        engine/xla_flags.py). Updates, and zero's gather-back, run at the
        tail gated on the fp16 finite flag like the other bodies. Same
        call/return contract as _build_step / _build_step_zero."""
        plan = self._overlap_plan
        plist = self._plist
        update_fn = self._update_fn
        loss_raw = self._loss_raw
        wds = self._wds
        trainable = self._trainable
        mesh = self.mesh
        ax = self.batch_axis
        ndp = self._dp_degree
        zero = self._zero
        comm = self._comm_dtype
        cdt = self.compute_dtype
        scaled = self._scaler is not None
        buckets = self._zero_plan if zero else self._overlap_buckets
        in_bucket = frozenset(i for b in buckets for i in b.indices)
        seg_of = plan.segment_of_slot
        buckets_by_seg: Dict[int, List[int]] = {}
        for bi, b in enumerate(buckets):
            owners = {seg_of[i] for i in b.indices}
            if len(owners) != 1:  # plan_buckets boundaries guarantee this
                raise MXNetError(
                    f"bucket {bi} spans segments {sorted(owners)}")
            buckets_by_seg.setdefault(owners.pop(), []).append(bi)

        # one pure apply per chain block; BN aux concatenates in forward
        # order, preserving the unsegmented builders' aux contract
        aux_orders: List[List[Parameter]] = []
        seg_applies = []
        for seg in plan.segments:
            apps = []
            for blk, uses in zip(seg.blocks, seg.block_uses):
                order: List[Parameter] = []
                aux_orders.append(order)
                sub = [plist[i] for i in uses]
                pos_in_seg = [seg.uses.index(i) for i in uses]
                apps.append((_make_apply_fn(blk, sub, train=True,
                                            aux_order_out=order),
                             pos_in_seg))
            seg_applies.append(apps)

        def _low(a):
            if cdt is not None and jnp.issubdtype(a.dtype, jnp.floating):
                return a.astype(cdt)
            return a

        def body(params, opt_state, key, x, y, lr, t, loss_scale):
            # x/y are the device-local batch tiles; params replicated
            if zero:
                bucket_carry, extra_state = opt_state
            pos = lax.axis_index(ax)
            kk = jax.random.wrap_key_data(key.astype(jnp.uint32),
                                          impl="threefry2x32")
            key_local = jax.random.key_data(jax.random.fold_in(kk, pos))

            def run_segment(s, seg_params, h):
                seg_aux = []
                for apply_b, idxs in seg_applies[s]:
                    out, aux_b = apply_b(
                        key_local, [_low(seg_params[j]) for j in idxs], h)
                    h = out[0] if isinstance(out, tuple) else out
                    seg_aux.extend(aux_b)
                return h, seg_aux

            # forward: one vjp per segment, pullbacks saved — the chunked
            # analog of value_and_grad's single backward
            pulls = []
            aux: List[Any] = []
            h = _low(x)
            for s, seg in enumerate(plan.segments):
                seg_params = [params[i] for i in seg.uses]
                if s == 0:  # close over the batch: no d/dx at the stem
                    h, pull, aux_s = jax.vjp(
                        functools.partial(run_segment, s, h=h),
                        seg_params, has_aux=True)
                else:
                    h, pull, aux_s = jax.vjp(
                        functools.partial(run_segment, s),
                        seg_params, h, has_aux=True)
                pulls.append(pull)
                aux.extend(aux_s)

            pred = h[0] if isinstance(h, tuple) else h
            lossv = loss_raw(pred, y)  # mean over the LOCAL batch
            _, loss_pull = jax.vjp(
                lambda hh: loss_raw(hh, y) * loss_scale, pred)

            # backward: replay pullbacks newest-first; a segment's owned
            # buckets reduce IMMEDIATELY, before older segments' dots
            inv = 1.0 / loss_scale
            grads: List[Any] = [None] * len(params)
            reduced: Dict[int, Any] = {}
            fin = jnp.bool_(True)
            (cot,) = loss_pull(jnp.ones_like(lossv))
            for s in range(len(plan.segments) - 1, -1, -1):
                seg = plan.segments[s]
                if s == 0:
                    (gseg,) = pulls[s](cot)
                else:
                    gseg, cot = pulls[s](cot)
                for j, i in enumerate(seg.uses):
                    g = gseg[j]
                    if scaled and jnp.issubdtype(g.dtype, jnp.floating):
                        g = g * inv
                    # a parameter shared across segments accumulates; its
                    # grad finalizes at its EARLIEST user (= owner)
                    grads[i] = g if grads[i] is None else grads[i] + g
                if scaled:
                    for i in seg.owned:
                        g = grads[i]
                        if trainable[i] and \
                                jnp.issubdtype(g.dtype, jnp.floating):
                            fin = jnp.logical_and(fin, jnp.all(
                                jnp.isfinite(g.astype(jnp.float32))))
                for bi in buckets_by_seg.get(s, ()):
                    flat_g = _zero.flatten_bucket(buckets[bi], grads)
                    if zero:
                        reduced[bi] = _zero.reduce_scatter_bucket(
                            flat_g, ax, ndp, comm)
                    else:
                        reduced[bi] = _overlap.allreduce_bucket(
                            flat_g, ax, ndp, comm)
            if scaled:
                finite = lax.pmin(fin.astype(jnp.int32), ax) \
                    .astype(jnp.bool_)
            else:
                finite = jnp.bool_(True)

            def _gate(new, old):
                # fp16 overflow step: keep the old buffer contents
                return jnp.where(finite, new, old) if scaled else new

            if zero:
                new_params = list(params)
                new_extra = list(extra_state)
                # trainables outside every bucket (non-float dtypes):
                # replicated update on the pmean'd gradient
                for i, (w, st) in enumerate(zip(params, extra_state)):
                    if not trainable[i] or i in in_bucket:
                        continue
                    gg = lax.pmean(grads[i], ax)
                    w2, s2 = update_fn(gg, w, st, t, lr,
                                       jnp.float32(wds[i]))
                    new_params[i] = _gate(w2.astype(w.dtype), w)
                    new_extra[i] = jax.tree_util.tree_map(_gate, s2, st) \
                        if scaled else s2
                new_carry = []
                for bi, (b, (wd_vec, st)) in enumerate(zip(buckets,
                                                           bucket_carry)):
                    g_shard = reduced[bi] / ndp
                    w_shard = _zero.shard_slice(
                        b, _zero.flatten_bucket(b, params), pos)
                    w2, s2 = update_fn(g_shard.astype(w_shard.dtype),
                                       w_shard, st, t, lr, wd_vec)
                    w2 = _gate(w2.astype(w_shard.dtype), w_shard)
                    s2 = jax.tree_util.tree_map(_gate, s2, st) \
                        if scaled else s2
                    full = _zero.all_gather_bucket(w2, ax)
                    for i, arr in _zero.unflatten_bucket(b, full):
                        new_params[i] = arr.astype(params[i].dtype)
                    new_carry.append((wd_vec, s2))
                new_state = (tuple(new_carry), tuple(new_extra))
            else:
                # unflatten the bucket-mean grads, then per-parameter
                # updates exactly as the plain step (per-tensor trust
                # ratios stay intact — buckets only carried the collective)
                gg_of: Dict[int, Any] = {}
                for bi, b in enumerate(buckets):
                    for i, arr in _zero.unflatten_bucket(
                            b, reduced[bi] / ndp):
                        gg_of[i] = arr
                new_params, new_state = [], []
                for i, (w, st) in enumerate(zip(params, opt_state)):
                    if not trainable[i]:
                        new_params.append(w)
                        new_state.append(st)
                        continue
                    gg = gg_of.get(i)
                    if gg is None:
                        gg = lax.pmean(grads[i], ax)
                    w2, s2 = update_fn(gg, w, st, t, lr,
                                       jnp.float32(wds[i]))
                    new_params.append(_gate(w2.astype(w.dtype), w))
                    new_state.append(
                        jax.tree_util.tree_map(_gate, s2, st)
                        if scaled else s2)
            glob_loss = lax.pmean(lossv, ax)
            aux = jax.tree_util.tree_map(
                lambda v: lax.pmean(v, ax)
                if jnp.issubdtype(v.dtype, jnp.floating) else v, aux)
            # cross-device-averaged BN running stats flow through the carry
            idx_of = {id(p): i for i, p in enumerate(plist)}
            aux_params = [p for order in aux_orders for p in order]
            for p, v in zip(aux_params, aux):
                j = idx_of.get(id(p))
                if j is not None and not trainable[j]:
                    new_params[j] = v.astype(new_params[j].dtype)
            return new_params, new_state, glob_loss, finite, aux

        dp = P(ax)
        rep = P()
        state_spec = (P(ax), rep) if zero else rep
        return _zero.shard_map_compat(
            body, mesh=mesh,
            in_specs=(rep, state_spec, rep, dp, dp, rep, rep, rep),
            out_specs=(rep, state_spec, rep, rep, rep))

    def _build_any_step(self):
        """Pick the step body for this trainer's configuration."""
        if self._compression:
            return self._build_step_compressed()
        if self._overlap:
            return self._build_step_overlap()
        if self._zero:
            return self._build_step_zero()
        return self._build_step(None, None)

    def _get_step(self, sig):
        donate = (0, 1, 2) if self._compression else (0, 1)
        return self._program.get(
            (sig,),
            lambda: jax.jit(self._build_any_step(), donate_argnums=donate))

    def _get_multi(self, sig, n, stacked):
        def build():
            compressed = self._compression is not None
            body = self._build_any_step()

            @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
            def multi(params, opt_state, resid, key_raw, x, y, lr, t0,
                      loss_scale):
                kk = jax.random.wrap_key_data(key_raw.astype(jnp.uint32),
                                              impl="threefry2x32")

                def sbody(carry, i):
                    params, opt_state, resid, t = carry
                    ki = jax.random.key_data(jax.random.fold_in(kk, i))
                    # per-step batch when x is stacked (n, B, ...), else reuse
                    xi = x[i] if stacked else x
                    yi = y[i] if stacked else y
                    if compressed:
                        p2, s2, r2, lossv, finite, aux = body(
                            params, opt_state, resid, ki, xi, yi, lr[i], t,
                            loss_scale)
                    else:
                        p2, s2, lossv, finite, aux = body(
                            params, opt_state, ki, xi, yi, lr[i], t,
                            loss_scale)
                        r2 = resid
                    return (p2, s2, r2, t + 1.0), (lossv, finite)

                (p, s, r, t_out), (losses, finites) = lax.scan(
                    sbody, (params, opt_state, resid, t0), jnp.arange(n))
                # advance the carried RNG stream and step counter ON DEVICE:
                # returning them lets run_steps keep every per-call scalar
                # device-resident (each host->device upload costs 50-100 ms
                # through the tunnel REGARDLESS of size — four small uploads
                # per call were ~5 ms/step of the ResNet bench; see
                # benchmark/opt_overhead_probe2.py)
                key_next = jax.random.key_data(
                    jax.random.fold_in(kk, jnp.int32(n)))
                return p, s, r, losses, jnp.all(finites), key_next, t_out
            return multi
        return self._program.get((sig, "multi", n), build)

    def run_steps(self, x, y, n, stacked=False):
        """Run `n` fused steps in ONE compiled computation (lax.scan over
        the step body) — the on-device training loop. Removes per-step host
        dispatch entirely; use with device-resident batches.

        stacked=False (default): x/y are one batch reused every step
        (benchmark mode). stacked=True: x/y carry a leading per-step axis
        (n, B, ...). The learning-rate schedule is honored per step (the
        scheduler is evaluated host-side for each of the n steps and the
        resulting lr array is scanned); the fp16 loss scale, however, is
        constant within one call — split into shorter calls if dynamic
        scaling needs to react faster. Returns the per-step loss array."""
        xr = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yr = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        self.optimizer.rescale_grad = 1.0
        if stacked and (xr.shape[0] != n or yr.shape[0] != n):
            raise MXNetError(
                f"run_steps(stacked=True): leading dim must be n={n}, got "
                f"{xr.shape[0]}/{yr.shape[0]}")
        sig = (xr.shape, str(xr.dtype), yr.shape, str(yr.dtype), stacked)
        fn = self._get_multi(sig, n, stacked)
        # Every host->device upload costs 50-100 ms through the tunneled
        # backend regardless of payload size, so all per-call scalars are
        # kept device-resident: lr/scale are cached by host value, and the
        # RNG key + step counter ride the donated carry (multi returns
        # their advanced values).
        lrs = []
        for i in range(n):
            self.optimizer.num_update = self._t + 1 + i
            lrs.append(float(self.optimizer.learning_rate))
        scale_val = float(self._scaler.loss_scale if self._scaler else 1.0)
        if self._is_multiprocess():
            # multi-process SPMD: plain host values (device_put cannot
            # target non-addressable devices; per-call upload cost is a
            # local-PJRT path there, not the tunneled one)
            lr_in = _np.asarray(lrs, _np.float32)
            scale_in = _np.float32(scale_val)
            key_in = _np.asarray(_rng.next_key_raw())
            t_in = _np.float32(self._t + 1)
        else:
            lr_sig = (tuple(lrs),)
            if getattr(self, "_lr_cache_sig", None) != lr_sig:
                self._lr_dev = jax.device_put(_np.asarray(lrs, _np.float32))
                self._lr_cache_sig = lr_sig
            if getattr(self, "_scale_cache_val", None) != scale_val:
                self._scale_dev = jax.device_put(_np.float32(scale_val))
                self._scale_cache_val = scale_val
            ep = _rng._host_state["epoch"]
            if getattr(self, "_key_dev", None) is None \
                    or self._key_epoch != ep:
                self._key_dev = jax.device_put(
                    _np.asarray(_rng.next_key_raw()))
                self._key_epoch = ep
            if getattr(self, "_t_dev_val", None) != self._t:
                self._t_dev = jax.device_put(_np.float32(self._t + 1))
                self._t_dev_val = self._t
            lr_in, scale_in = self._lr_dev, self._scale_dev
            key_in, t_in = self._key_dev, self._t_dev
        spec = self.data_spec
        if stacked:
            spec = P(None, *self.data_spec)
        xr = self._put_batch(xr, NamedSharding(self.mesh, P(*spec[:xr.ndim])))
        yr = self._put_batch(yr, NamedSharding(self.mesh, P(*spec[:yr.ndim])))
        cost_key = (sig, "multi", n)
        self._program.capture_cost(
            cost_key, fn, self._params_raw, self._opt_state,
            self._comp_resid, key_in, xr, yr, lr_in, t_in, scale_in,
            kind="dp_multi", overlap_expected=self._overlap)
        t_sp = time.perf_counter() if _tracing._ENABLED else 0.0
        with _telem.annotate("mx.dp.run_steps"), _sanitize.guard():
            (self._params_raw, self._opt_state, self._comp_resid, losses,
             finite, key_out, t_out) = fn(
                self._params_raw, self._opt_state, self._comp_resid,
                key_in, xr, yr, lr_in, t_in, scale_in)
        if _tracing._ENABLED:
            # dispatch-only span; the same name as the TraceAnnotation
            # region so host and device timelines line up in Perfetto
            _tracing.record_span("mx.dp.run_steps", t_sp,
                                 time.perf_counter(), steps=n,
                                 step=self._t, source="data_parallel")
        # one run_steps call = one in-flight entry (n fused steps inside a
        # single executable); telemetry after admission, as in step()
        self._window.admit(losses)
        if _telem._ENABLED:
            per_step_batch = xr.shape[1] if stacked else xr.shape[0]
            self._record_telemetry(sig, per_step_batch * n, n,
                                   flops_key=cost_key)
        self._t += n
        if not self._is_multiprocess():
            self._key_dev, self._t_dev = key_out, t_out
            self._t_dev_val = self._t
        self.optimizer.num_update = self._t
        if self._scaler is not None:
            self._scaler.update_from_step(finite)
        return losses

    def step(self, x, y, batch_size=None):
        """Run one fused training step; x/y are NDArrays (global batch)."""
        xr = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yr = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        bs = batch_size or xr.shape[0]
        self.optimizer.rescale_grad = 1.0
        sig = (xr.shape, str(xr.dtype), yr.shape, str(yr.dtype))
        fn = self._get_step(sig)
        self._t += 1
        self.optimizer.num_update = self._t
        lr = _np.float32(self.optimizer.learning_rate)
        key = _np.asarray(_rng.next_key_raw())
        xr = self._put_batch(xr, NamedSharding(self.mesh, self.data_spec))
        y_spec = self.data_spec if yr.ndim >= len(self.data_spec) \
            else P(*self.data_spec[:yr.ndim])
        yr = self._put_batch(yr, NamedSharding(self.mesh, y_spec))
        scale = _np.float32(self._scaler.loss_scale if self._scaler else 1.0)
        t_in = _np.float32(self._t)
        if not self._is_multiprocess():
            # EXPLICIT placement of the per-step host scalars: the uploads
            # happen either way, but implicit numpy->device transfers are
            # exactly what sanitize mode's transfer guard rejects
            key, lr, t_in, scale = jax.device_put(
                (key, lr, t_in, scale), NamedSharding(self.mesh, P()))
        call_args = ((self._params_raw, self._opt_state, self._comp_resid,
                      key, xr, yr, lr, t_in, scale) if self._compression
                     else (self._params_raw, self._opt_state, key, xr, yr,
                           lr, t_in, scale))
        # cost_analysis FLOPs of the fused step, captured once per
        # signature at artifact-build time (AOT lower shares XLA caches)
        self._program.capture_cost(sig, fn, *call_args, kind="dp_step",
                                   overlap_expected=self._overlap)
        t_sp = time.perf_counter() if _tracing._ENABLED else 0.0
        with _telem.annotate("mx.dp.step"), _sanitize.guard():
            if self._compression:
                (self._params_raw, self._opt_state, self._comp_resid, lossv,
                 finite, aux) = fn(*call_args)
            else:
                self._params_raw, self._opt_state, lossv, finite, aux = fn(
                    *call_args)
        if _tracing._ENABLED:
            # the step-dispatch span, same name as the TraceAnnotation
            # region; admit/drain pacing is the window's own span
            _tracing.record_span("mx.dp.step", t_sp, time.perf_counter(),
                                 step=self._t, source="data_parallel")
        if self._scaler is not None:
            # fp16 dynamic loss scaling reads the finite flag per step —
            # the one sync the overlap window cannot remove (documented in
            # docs/input_pipeline.md "when overlap cannot help")
            self._scaler.update_from_step(finite)
        # non-blocking dispatch: admit the step into the bounded window
        # (blocks on the (i-K)th step, never this one), THEN record
        # telemetry — the interval-based step timing thereby runs at
        # completion pace under backpressure instead of dispatch pace, and
        # never adds a sync of its own
        self._window.admit(lossv)
        if _telem._ENABLED:
            self._record_telemetry(sig, bs, 1)
        return _feed.PendingScalar(lossv)

    def drain(self):
        """Block until every dispatched step completed — the designed
        epoch/eval-boundary sync point for an overlapped loop that
        collected PendingScalar losses."""
        self._window.drain()

    def sync(self):
        """Write device params back into the gluon Parameters."""
        self.drain()
        for p, w in zip(self._plist, self._params_raw):
            p._data._set_data(w)

    def save_checkpoint(self, prefix: str):
        self.sync()
        self.net.save_parameters(prefix + ".params")

    # -- elastic fault tolerance ---------------------------------------------
    def state_dict(self):
        """Full training state in the elastic snapshot schema
        ``{"leaves": {name: device array}, "meta": {...}}`` — params,
        optimizer state (incl. per-replica ZeRO shards), RNG, step/schedule
        counters, loss-scaler state. Feed it to
        ``elastic.SnapshotManager.save`` (async, no gather) or to another
        trainer's ``load_state_dict``."""
        from ..elastic import state as _estate
        return _estate.capture(self)

    def load_state_dict(self, snapshot):
        """Install a ``state_dict()``/manifest snapshot into this trainer,
        resharding onto this trainer's mesh if it differs from the saving
        run's (see docs/checkpointing.md for the resharding rules)."""
        from ..elastic import state as _estate
        self.drain()
        leaves, meta = snapshot["leaves"], snapshot["meta"]
        _estate.install(self, meta, leaves.__getitem__, set(leaves))
        return self

    @property
    def num_update(self):
        return self._t
