"""Sequence/context parallel attention — re-exported from ops.attention
(implementation lives there so the op registry can record VJPs for the
eager tape; see that module for the design notes)."""
from ..ops.attention import (blockwise_attention, ring_attention,
                             ulysses_attention, flash_attention_op)

__all__ = ["blockwise_attention", "ring_attention", "ulysses_attention",
           "flash_attention_op"]
