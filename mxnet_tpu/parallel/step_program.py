"""Shared step-program plumbing for the fused trainers.

`DataParallelTrainer` and `PipelineTrainer` both follow the same executable
lifecycle: a config-fingerprinted key base names the trainer's compiled
step family, per-signature variants resolve through the PROCESS-WIDE engine
cache (so N same-config trainers share one executable instead of each
holding a private jit), the XLA cost model is captured once per variant at
build time, and every execution is booked against a roofline-ledger region
derived from the same fingerprint. `StepProgram` owns that lifecycle;
the trainers keep only their step bodies.

Key layout (docs/compilation.md "fused-step fingerprints"):

    key_base = ("dp_step" | "pp_step",
                engine.structural_fingerprint(net),
                engine.config_fingerprint(**trainer_config))
    cache key = key_base + variant        # variant = (sig,) or (sig, ...)
    region    = f"{label}#{sha1(repr((key_base, cost_key)))[:6]}"

The region digest covers the FULL compile key, so two configurations that
compile apart ledger apart, while any number of same-config trainers
aggregate into one row — the contract tests/test_roofline.py pins for dp
and tests/test_pipeline_1f1b.py pins for pp.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

from .. import engine as _engine
from .. import telemetry as _telem

__all__ = ["StepProgram"]


class StepProgram:
    """Engine-cache-backed executable family for one trainer configuration.

    label:    readable region prefix, e.g. ``dp.step[BertModel]``.
    key_base: the fingerprint tuple above; equal key_base => shared
              executables, shared cost captures, shared ledger rows.
    """

    __slots__ = ("label", "key_base", "_local", "_costs", "_regions")

    def __init__(self, label: str, key_base: Tuple):
        self.label = label
        self.key_base = key_base
        self._local: Dict[Any, Callable] = {}
        self._costs: Dict[Any, Dict[str, float]] = {}
        self._regions: Dict[Any, str] = {}

    @property
    def fingerprint(self) -> str:
        """Stable digest of the trainer configuration (network structure +
        trainer config, NOT the mesh placement of a particular run).
        Elastic snapshots record it; ``resume_or_init`` compares it to
        classify a boot as same-program "resumed" vs "resharded"."""
        return _engine.region_digest(self.key_base, "program")

    # -- executables --------------------------------------------------------
    def get(self, variant: Tuple, build: Callable[[], Callable]):
        """The compiled step for ``key_base + variant``: local memo ->
        engine.lookup -> build() + engine.insert. ``build`` returns the
        final jitted callable (donation decided by the caller); the engine
        cache owns it, so a second same-config trainer scores a cache hit
        instead of a second compile."""
        fn = self._local.get(variant)
        if fn is None:
            ck = self.key_base + variant
            fn = _engine.lookup(ck)
            if fn is None:
                fn = _engine.insert(ck, build())
            self._local[variant] = fn
        return fn

    # -- roofline regions ---------------------------------------------------
    def region(self, cost_key) -> str:
        """Ledger row key: readable label + digest of (key_base, cost_key)."""
        name = self._regions.get(cost_key)
        if name is None:
            digest = _engine.region_digest(self.key_base, cost_key)
            name = f"{self.label}#{digest}"
            self._regions[cost_key] = name
        return name

    # -- cost capture -------------------------------------------------------
    def capture_cost(self, cost_key, fn, *args, kind: str = "artifact",
                     overlap_expected: bool = False):
        """XLA cost_analysis/memory_analysis of ``fn`` at ``args``, captured
        ONCE per cost_key and only while telemetry is enabled (the AOT
        lower+compile shares XLA's compilation caches with the real call).
        The same compile feeds the HLO hazard audit, fingerprinted under
        this program's ledger region (engine/hlo_audit.py);
        ``overlap_expected`` marks artifacts whose collectives are supposed
        to compile to async start/done pairs (overlap_grads on)."""
        if _telem._ENABLED and cost_key not in self._costs:
            self._costs[cost_key] = _engine.estimate_cost(
                fn, *args, kind=kind, region=self.region(cost_key),
                overlap_expected=overlap_expected)
        return self._costs.get(cost_key, {})

    def cost(self, cost_key) -> Dict[str, float]:
        return self._costs.get(cost_key, {})
