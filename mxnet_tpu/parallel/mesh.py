"""Device-mesh helpers (the TPU replacement for ctx lists / kvstore topology).

reference analog: src/kvstore/gpu_topology.h built reduction trees from PCIe
adjacency; on TPU the torus is expressed as a jax.sharding.Mesh and XLA lays
collectives on ICI rings itself.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Union

import jax
import numpy as _np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

_DEFAULT_MESH: Optional[Mesh] = None


def axis_size(name: str) -> int:
    """Static size of a mapped mesh axis, inside shard_map/pmap bodies:
    ``jax.lax.axis_size`` where it exists, else the constant-folding
    ``psum(1, name)`` idiom (returns a Python int on both)."""
    fn = getattr(jax.lax, "axis_size", None)
    return fn(name) if fn is not None else jax.lax.psum(1, name)


def require_axis(mesh: Mesh, name: str, role: str = "this trainer") -> int:
    """Validate that `name` is an axis of `mesh`; returns its size."""
    if name not in mesh.shape:
        from ..base import MXNetError
        raise MXNetError(
            f"mesh has no {name!r} axis for {role}: {dict(mesh.shape)}")
    return mesh.shape[name]


def make_mesh(axes: Union[Dict[str, int], Sequence[int]], names: Optional[Sequence[str]] = None,
              devices=None) -> Mesh:
    """make_mesh({'dp': 4, 'tp': 2}) or make_mesh((4, 2), ('dp', 'tp'))."""
    if isinstance(axes, dict):
        names = tuple(axes.keys())
        shape = tuple(axes.values())
    else:
        shape = tuple(axes)
        names = tuple(names or [f"axis{i}" for i in range(len(shape))])
    devices = devices if devices is not None else jax.devices()
    n = int(_np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    dev_array = _np.asarray(devices[:n]).reshape(shape)
    return Mesh(dev_array, names)


def local_mesh(dp: Optional[int] = None, name: str = "dp") -> Mesh:
    """1-D data-parallel mesh over all local devices."""
    devs = jax.devices()
    dp = dp or len(devs)
    return make_mesh({name: dp}, devices=devs)


def set_default_mesh(mesh: Optional[Mesh]):
    global _DEFAULT_MESH
    _DEFAULT_MESH = mesh


def current_mesh() -> Mesh:
    global _DEFAULT_MESH
    if _DEFAULT_MESH is None:
        _DEFAULT_MESH = local_mesh()
    return _DEFAULT_MESH


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, axis: str = "dp", ndim: int = 2) -> NamedSharding:
    """Batch dim sharded over `axis`, rest replicated."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def param_sharding(mesh: Mesh, spec: Optional[PartitionSpec]) -> NamedSharding:
    return NamedSharding(mesh, spec if spec is not None else P())
