"""Parallelism over jax.sharding meshes (SURVEY.md §2.4 / §5-h).

The reference's entire distributed stack (Comm trees, NCCL kvstore, ps-lite
parameter server — src/kvstore/) collapses here into XLA collectives driven
by sharding annotations:

  - data parallel:   batch sharded over 'dp'; grad allreduce inserted by XLA
  - tensor parallel: weight matrices sharded over 'tp' (Megatron col/row);
    tp_mode='partitioned' runs the compute partitioned with manual
    activation collectives instead of gathering weights (megatron.py)
  - sequence/context parallel: ring attention over 'sp' via ppermute;
    sequence_parallel=True seq-shards the LN/dropout/residual regions
    between the partitioned matmuls
  - pipeline:        layer stages over 'pp' with microbatch scan
  - multi-host:      same collectives; DCN is just an outer mesh axis

Capability uplift vs the reference (which had none of TP/PP/SP — SURVEY §2.4).
"""
from .mesh import (make_mesh, local_mesh, replicate, shard_batch, P,
                   current_mesh, set_default_mesh, require_axis)
from .data_parallel import DataParallelTrainer, functional_optimizer
from .ring_attention import ring_attention, blockwise_attention
from .tensor_parallel import (column_parallel_spec, row_parallel_spec,
                              shard_params_megatron, tp_shard_dim,
                              gather_tp, slice_tp, shard_rules, apply_rules,
                              DEFAULT_RULES)
from .megatron import (copy_to_tp, reduce_from_tp, gather_from_sp,
                       scatter_to_sp, vocab_parallel_embedding,
                       vocab_parallel_cross_entropy)
from .pipeline import (pipeline_spec, pipeline_apply, gpipe_schedule,
                       schedule_1f1b, PipelineTrainer)
from .step_program import StepProgram
from .moe import (moe_ffn, expert_parallel_moe, topk_gating,
                  load_balancing_loss, load_balance_loss, dropped_tokens,
                  wire_all_to_all, all_to_all_wire_bytes, moe_capacity,
                  expert_axis, collect_metrics)

__all__ = ["make_mesh", "local_mesh", "replicate", "shard_batch", "P",
           "current_mesh", "set_default_mesh", "require_axis",
           "DataParallelTrainer",
           "functional_optimizer", "ring_attention", "blockwise_attention",
           "column_parallel_spec", "row_parallel_spec", "shard_params_megatron",
           "tp_shard_dim", "gather_tp", "slice_tp",
           "shard_rules", "apply_rules", "DEFAULT_RULES",
           "copy_to_tp", "reduce_from_tp", "gather_from_sp", "scatter_to_sp",
           "vocab_parallel_embedding", "vocab_parallel_cross_entropy",
           "pipeline_spec", "pipeline_apply", "gpipe_schedule",
           "schedule_1f1b", "PipelineTrainer", "StepProgram",
           "moe_ffn", "expert_parallel_moe", "topk_gating",
           "load_balancing_loss", "load_balance_loss", "dropped_tokens",
           "wire_all_to_all", "all_to_all_wire_bytes", "moe_capacity",
           "expert_axis", "collect_metrics"]
