"""Mixture-of-Experts with expert parallelism (capability uplift: the
reference has no EP/MoE at all — SURVEY.md §2.4).

TPU-native design: capacity-based top-k gating builds fixed-shape dispatch/
combine tensors (no dynamic shapes — dropped tokens are the standard
capacity-overflow semantics), expert FFNs run as one batched einsum, and
expert parallelism shards the expert dimension over an 'ep' mesh axis with
two `lax.all_to_all` exchanges (token -> expert shard -> token), riding ICI.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .mesh import axis_size as _axis_size


def topk_gating(logits, top_k: int, capacity: int):
    """Top-k capacity gating (Switch/GShard style).

    logits: (N, E). Returns (dispatch (N, E, C) float 0/1, combine (N, E, C)).
    Token n's k-th choice lands in expert e's slot c if fewer than C earlier
    tokens chose e; overflow tokens are dropped (their combine weight is 0).
    """
    N, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    _, idx = lax.top_k(probs, top_k)                     # (N, K)

    dispatch = jnp.zeros((N, E, capacity), logits.dtype)
    combine = jnp.zeros((N, E, capacity), logits.dtype)
    counts = jnp.zeros((E,), jnp.int32)
    for k in range(top_k):
        onehot = jax.nn.one_hot(idx[:, k], E, dtype=jnp.int32)   # (N, E)
        pos_in_e = jnp.cumsum(onehot, axis=0) - onehot           # prior count
        pos = jnp.sum(onehot * (pos_in_e + counts[None, :]), axis=1)  # (N,)
        e_sel = idx[:, k]
        fits = pos < capacity
        slot = jax.nn.one_hot(jnp.where(fits, pos, capacity), capacity,
                              dtype=logits.dtype)                # (N, C)
        d_k = jax.nn.one_hot(e_sel, E, dtype=logits.dtype)[:, :, None] * \
            slot[:, None, :]                                     # (N, E, C)
        d_k = d_k * fits[:, None, None].astype(logits.dtype)
        dispatch = dispatch + d_k
        gate = jnp.take_along_axis(probs, e_sel[:, None], axis=1)[:, 0]
        combine = combine + d_k * gate[:, None, None]
        counts = counts + jnp.sum(onehot, axis=0)
    return dispatch, combine


def moe_ffn(x, gate_w, w1, w2, *, top_k: int = 2,
            capacity_factor: float = 1.5, activation=jax.nn.relu,
            normalize_gates: bool = True):
    """Dense (single-shard) MoE FFN.

    x (N, D); gate_w (D, E); w1 (E, D, H); w2 (E, H, D). Returns (N, D).
    """
    N, D = x.shape
    E = gate_w.shape[1]
    capacity = max(1, int(capacity_factor * N * top_k / E))
    logits = x @ gate_w
    dispatch, combine = topk_gating(logits, top_k, capacity)
    if normalize_gates:
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(denom, 1e-9)
    expert_in = jnp.einsum("nd,nec->ecd", x, dispatch)     # (E, C, D)
    h = activation(jnp.einsum("ecd,edh->ech", expert_in, w1))
    expert_out = jnp.einsum("ech,ehd->ecd", h, w2)         # (E, C, D)
    return jnp.einsum("ecd,nec->nd", expert_out, combine)


def expert_parallel_moe(x, gate_w, w1_local, w2_local, *, axis_name: str,
                        top_k: int = 2, capacity_factor: float = 1.5,
                        activation=jax.nn.relu, normalize_gates: bool = True):
    """Expert-parallel MoE FFN — call inside shard_map over `axis_name`.

    Tokens are sharded over the axis (x is the LOCAL (Nl, D) shard); experts
    are sharded too (w1_local (El, D, H), El = E / axis_size). Dataflow:

      gate locally over ALL E experts
      -> all_to_all: each device collects the slots destined to ITS experts
      -> batched expert FFN on local experts
      -> all_to_all back -> combine locally

    Same math as moe_ffn on the gathered arrays (up to capacity rounding).
    """
    n_dev = _axis_size(axis_name)
    Nl, D = x.shape
    El = w1_local.shape[0]
    E = El * n_dev
    capacity = max(1, int(capacity_factor * Nl * top_k / E))

    logits = x @ gate_w                                     # (Nl, E)
    dispatch, combine = topk_gating(logits, top_k, capacity)
    if normalize_gates:
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(denom, 1e-9)
    expert_in = jnp.einsum("nd,nec->ecd", x, dispatch)      # (E, C, D)
    # regroup experts by owner device and exchange: after all_to_all, axis 0
    # indexes the SOURCE device and axis 1 the local expert
    expert_in = expert_in.reshape(n_dev, El, capacity, D)
    expert_in = lax.all_to_all(expert_in, axis_name, split_axis=0,
                               concat_axis=0, tiled=False)
    # (n_dev_src, El, C, D) -> (El, n_dev_src * C, D)
    gathered = jnp.moveaxis(expert_in, 0, 1).reshape(El, n_dev * capacity, D)
    h = activation(jnp.einsum("ecd,edh->ech", gathered, w1_local))
    out = jnp.einsum("ech,ehd->ecd", h, w2_local)           # (El, n_dev*C, D)
    # reverse exchange: send each source device its slots back
    out = jnp.moveaxis(out.reshape(El, n_dev, capacity, D), 1, 0)
    out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)                       # (n_dev, El, C, D)
    out = out.reshape(E, capacity, D)
    return jnp.einsum("ecd,nec->nd", out, combine)


def load_balancing_loss(logits, top_k: int = 2):
    """Auxiliary load-balance loss (Switch Transformer eq. 4): encourages
    uniform expert utilization. Returns a scalar >= 1/E."""
    N, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    _, idx = lax.top_k(probs, top_k)
    me = jnp.mean(probs, axis=0)                            # mean router prob
    ce = jnp.mean(jax.nn.one_hot(idx[:, 0], E), axis=0)     # token fraction
    return E * jnp.sum(me * ce)
