"""Mixture-of-Experts with expert parallelism (capability uplift: the
reference has no EP/MoE at all — SURVEY.md §2.4).

TPU-native design: capacity-based top-k gating builds fixed-shape dispatch/
combine tensors (no dynamic shapes — dropped tokens are the standard
capacity-overflow semantics), expert FFNs run as one batched einsum, and
expert parallelism shards the expert dimension over an 'ep' mesh axis with
two `lax.all_to_all` exchanges (token -> expert shard -> token), riding ICI.
The exchanges optionally compress onto the same bf16/int8 comm wire the
ZeRO gradient collectives use (EQuARX, arXiv:2506.17615) — see
``wire_all_to_all`` / ``MXNET_TPU_COMM_DTYPE``.

End-to-end training of these layers lives in ``mxnet_tpu.recipes.moe``
(docs/large_models.md); this module stays a pure function library.
"""
from __future__ import annotations

import contextlib
import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from .mesh import axis_size as _axis_size


def topk_gating(logits, top_k: int, capacity: int):
    """Top-k capacity gating (Switch/GShard style).

    logits: (N, E). Returns (dispatch (N, E, C) float 0/1, combine (N, E, C)).
    Token n's k-th choice lands in expert e's slot c if fewer than C earlier
    tokens chose e; overflow tokens are dropped (their combine weight is 0).

    Determinism contract (parity tests depend on it):

      - expert ties break toward the LOWER expert index — ``lax.top_k``
        returns the first maximal index on equal probabilities, on every
        backend;
      - capacity slots are claimed in TOKEN order (the running ``cumsum``
        over axis 0), so for a fixed token ordering the overflow set is a
        pure function of the logits — two runs (or two devices gating the
        same shard) always drop the same tokens;
      - choice ranks fill sequentially: all k=0 assignments claim slots
        before any k=1 assignment of the same call (the ``counts`` carry).

    Nothing here samples or depends on iteration order of a hash map, so
    gating is bitwise-reproducible for identical inputs.
    """
    N, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    _, idx = lax.top_k(probs, top_k)                     # (N, K)

    dispatch = jnp.zeros((N, E, capacity), logits.dtype)
    combine = jnp.zeros((N, E, capacity), logits.dtype)
    counts = jnp.zeros((E,), jnp.int32)
    for k in range(top_k):
        onehot = jax.nn.one_hot(idx[:, k], E, dtype=jnp.int32)   # (N, E)
        pos_in_e = jnp.cumsum(onehot, axis=0) - onehot           # prior count
        pos = jnp.sum(onehot * (pos_in_e + counts[None, :]), axis=1)  # (N,)
        e_sel = idx[:, k]
        fits = pos < capacity
        slot = jax.nn.one_hot(jnp.where(fits, pos, capacity), capacity,
                              dtype=logits.dtype)                # (N, C)
        d_k = jax.nn.one_hot(e_sel, E, dtype=logits.dtype)[:, :, None] * \
            slot[:, None, :]                                     # (N, E, C)
        d_k = d_k * fits[:, None, None].astype(logits.dtype)
        dispatch = dispatch + d_k
        gate = jnp.take_along_axis(probs, e_sel[:, None], axis=1)[:, 0]
        combine = combine + d_k * gate[:, None, None]
        counts = counts + jnp.sum(onehot, axis=0)
    return dispatch, combine


def load_balance_loss(probs, dispatch):
    """Switch-style auxiliary load-balancing loss from the gate's outputs.

    probs: (N, E) router probabilities; dispatch: (N, E, C) assignment mask
    from ``topk_gating``. ``E * sum_e f_e * P_e`` where ``f_e`` is the
    fraction of realized (post-capacity) assignments that landed on expert
    e and ``P_e`` the mean router probability — minimized (= 1) at uniform
    routing, so adding ``aux_weight * load_balance_loss`` to the task loss
    pushes the router toward balance. Differentiable through ``probs``
    only (the dispatch mask is a hard assignment; its gradient is zero
    a.e., matching the Switch Transformer estimator).
    """
    E = probs.shape[1]
    assigned = jnp.sum(dispatch, axis=2)                      # (N, E) 0/1
    denom = jnp.maximum(jnp.sum(assigned), 1.0)
    f = lax.stop_gradient(jnp.sum(assigned, axis=0) / denom)  # realized share
    p = jnp.mean(probs, axis=0)
    return E * jnp.sum(f * p)


def dropped_tokens(dispatch, n_tokens: int, top_k: int):
    """Capacity-overflow count: (token, choice) assignments that found no
    free slot. Scalar int32, ``0 <= dropped <= N * top_k``. Surfaced by the
    MoE recipe trainer on ``mx_moe_dropped_tokens_total``."""
    made = jnp.sum(dispatch.astype(jnp.float32))
    return (jnp.int32(n_tokens * top_k) - made.astype(jnp.int32))


# ---------------------------------------------------------------------------
# Comm-wire all_to_all: the dispatch/combine exchanges ride the same
# bf16/int8 wire as the ZeRO gradient collectives (zero.py, EQuARX
# arXiv:2506.17615). all_to_all with split_axis=0/concat_axis=0 is a pure
# block permutation, so it is its own transpose: the custom VJP runs the
# SAME compressed exchange on the cotangent.
# ---------------------------------------------------------------------------

def _a2a(x, axis_name):
    return lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)


def _wire_exchange(x, axis_name, comm_dtype):
    """One compressed all_to_all. x: (n_dev, ...) local block layout."""
    if comm_dtype is None:
        return _a2a(x, axis_name)
    if comm_dtype == "bfloat16":
        return _a2a(x.astype(jnp.bfloat16), axis_name).astype(x.dtype)
    if comm_dtype == "int8":
        # per-destination-row chunk scaling (one amax per outbound block,
        # the zero.py reduce_scatter idiom): scale rides the wire as f32
        n = x.shape[0]
        flat = x.reshape(n, -1)
        amax = jnp.max(jnp.abs(flat), axis=1, keepdims=True)
        scale = jnp.maximum(amax / 127.0, 1e-12)
        q = jnp.clip(jnp.round(flat / scale), -127, 127).astype(jnp.int8)
        q = _a2a(q, axis_name)
        scale = _a2a(scale, axis_name)
        return (q.astype(x.dtype) * scale).reshape(x.shape)
    raise ValueError(f"unsupported comm_dtype {comm_dtype!r}")


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def wire_all_to_all(x, axis_name: str, comm_dtype: Optional[str] = None):
    """``lax.all_to_all(split_axis=0, concat_axis=0)`` over `axis_name`,
    optionally compressed on the wire (``comm_dtype`` None/'bfloat16'/
    'int8' — the ``MXNET_TPU_COMM_DTYPE`` vocabulary, canonicalized by
    ``zero.canonical_comm_dtype``). The backward exchange compresses the
    cotangent identically, so forward and backward wire volume match
    ``all_to_all_wire_bytes`` exactly."""
    return _wire_exchange(x, axis_name, comm_dtype)


def _wire_a2a_fwd(x, axis_name, comm_dtype):
    return _wire_exchange(x, axis_name, comm_dtype), None


def _wire_a2a_bwd(axis_name, comm_dtype, _res, g):
    return (_wire_exchange(g, axis_name, comm_dtype),)


wire_all_to_all.defvjp(_wire_a2a_fwd, _wire_a2a_bwd)


def moe_capacity(n_tokens_local: int, top_k: int, capacity_factor: float,
                 n_experts: int) -> int:
    """The per-expert slot count every gating call in this module uses."""
    return max(1, int(capacity_factor * n_tokens_local * top_k / n_experts))


def all_to_all_wire_bytes(n_tokens_local: int, d_model: int, *,
                          n_experts: int, top_k: int,
                          capacity_factor: float, ep: int,
                          comm_dtype: Optional[str] = None,
                          dtype="float32") -> int:
    """Exact per-device wire bytes of ONE dispatch/combine exchange.

    The exchanged tensor is (ep, El, C, D) = E*C*D elements per device; an
    all_to_all keeps 1/ep of it local, so (ep-1)/ep of the payload crosses
    the wire — the same (n-1)/n convention the ZeRO wire accounting uses
    (zero.reduce_scatter_wire_bytes). Compression changes the element size
    (bf16: 2, int8: 1 + one f32 scale per outbound row); ``comm_dtype``
    None means the payload dtype. Multiply by 4 * n_layers for a full MoE
    training step (dispatch + combine, forward + backward).
    """
    if ep <= 1:
        return 0
    cap = moe_capacity(n_tokens_local, top_k, capacity_factor, n_experts)
    elems = n_experts * cap * d_model
    if comm_dtype == "bfloat16":
        item = 2
        extra = 0
    elif comm_dtype == "int8":
        item = 1
        extra = ep * 4                      # one f32 scale per outbound row
    else:
        item = _np.dtype(dtype).itemsize
        extra = 0
    return elems * item * (ep - 1) // ep + extra


# ---------------------------------------------------------------------------
# MoE layers
# ---------------------------------------------------------------------------

def moe_ffn(x, gate_w, w1, w2, *, top_k: int = 2,
            capacity_factor: float = 1.5, activation=jax.nn.relu,
            normalize_gates: bool = True, return_aux: bool = False):
    """Dense (single-shard) MoE FFN.

    x (N, D); gate_w (D, E); w1 (E, D, H); w2 (E, H, D). Returns (N, D),
    or ``(y, {"aux_loss", "dropped"})`` with ``return_aux=True`` — the
    Switch load-balance loss and the capacity-overflow count for this call.
    """
    N, D = x.shape
    E = gate_w.shape[1]
    capacity = moe_capacity(N, top_k, capacity_factor, E)
    logits = x @ gate_w
    probs = jax.nn.softmax(logits, axis=-1)
    dispatch, combine = topk_gating(logits, top_k, capacity)
    if normalize_gates:
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(denom, 1e-9)
    expert_in = jnp.einsum("nd,nec->ecd", x, dispatch)     # (E, C, D)
    h = activation(jnp.einsum("ecd,edh->ech", expert_in, w1))
    expert_out = jnp.einsum("ech,ehd->ecd", h, w2)         # (E, C, D)
    y = jnp.einsum("ecd,nec->nd", expert_out, combine)
    if not return_aux:
        return y
    aux = {"aux_loss": load_balance_loss(probs, dispatch),
           "dropped": dropped_tokens(dispatch, N, top_k)}
    return y, aux


def expert_parallel_moe(x, gate_w, w1_local, w2_local, *, axis_name: str,
                        top_k: int = 2, capacity_factor: float = 1.5,
                        activation=jax.nn.relu, normalize_gates: bool = True,
                        comm_dtype: Optional[str] = None,
                        return_aux: bool = False):
    """Expert-parallel MoE FFN — call inside shard_map over `axis_name`.

    Tokens are sharded over the axis (x is the LOCAL (Nl, D) shard); experts
    are sharded too (w1_local (El, D, H), El = E / axis_size). Dataflow:

      gate locally over ALL E experts
      -> all_to_all: each device collects the slots destined to ITS experts
      -> batched expert FFN on local experts
      -> all_to_all back -> combine locally

    Same math as moe_ffn on the gathered arrays (up to capacity rounding);
    with ``axis_size == 1`` the exchanges are identities and the result
    equals ``moe_ffn`` bitwise. ``comm_dtype`` compresses both exchanges on
    the wire (``wire_all_to_all``).
    """
    n_dev = _axis_size(axis_name)
    Nl, D = x.shape
    El = w1_local.shape[0]
    E = El * n_dev
    capacity = moe_capacity(Nl, top_k, capacity_factor, E)

    logits = x @ gate_w                                     # (Nl, E)
    probs = jax.nn.softmax(logits, axis=-1)
    dispatch, combine = topk_gating(logits, top_k, capacity)
    if normalize_gates:
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(denom, 1e-9)
    expert_in = jnp.einsum("nd,nec->ecd", x, dispatch)      # (E, C, D)
    # regroup experts by owner device and exchange: after all_to_all, axis 0
    # indexes the SOURCE device and axis 1 the local expert
    expert_in = expert_in.reshape(n_dev, El, capacity, D)
    expert_in = wire_all_to_all(expert_in, axis_name, comm_dtype)
    # (n_dev_src, El, C, D) -> (El, n_dev_src * C, D)
    gathered = jnp.moveaxis(expert_in, 0, 1).reshape(El, n_dev * capacity, D)
    h = activation(jnp.einsum("ecd,edh->ech", gathered, w1_local))
    out = jnp.einsum("ech,ehd->ecd", h, w2_local)           # (El, n_dev*C, D)
    # reverse exchange: send each source device its slots back
    out = jnp.moveaxis(out.reshape(El, n_dev, capacity, D), 1, 0)
    out = wire_all_to_all(out, axis_name, comm_dtype)       # (n_dev, El, C, D)
    out = out.reshape(E, capacity, D)
    y = jnp.einsum("ecd,nec->nd", out, combine)
    if not return_aux:
        return y
    aux = {"aux_loss": load_balance_loss(probs, dispatch),
           "dropped": dropped_tokens(dispatch, Nl, top_k)}
    return y, aux


def load_balancing_loss(logits, top_k: int = 2):
    """Auxiliary load-balance loss (Switch Transformer eq. 4) from raw
    logits, pre-capacity (kept for callers that gate elsewhere; the
    post-capacity variant is ``load_balance_loss``). Scalar >= 1/E."""
    N, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    _, idx = lax.top_k(probs, top_k)
    me = jnp.mean(probs, axis=0)                            # mean router prob
    ce = jnp.mean(jax.nn.one_hot(idx[:, 0], E), axis=0)     # token fraction
    return E * jnp.sum(me * ce)


# ---------------------------------------------------------------------------
# Trace-time plumbing for model cells (models/moe_transformer.py): which
# mesh axis the MoE layers should dispatch over, and where they report
# their per-call aux loss / dropped count. Both are plain trace-time
# context stacks — the recipe trainer opens them around the apply-fn call
# inside its loss function, so the collected values are tracers belonging
# to that trace and flow into the fused step's outputs.
# ---------------------------------------------------------------------------

class _ExpertCtx:
    __slots__ = ("axis_name", "comm_dtype")

    def __init__(self, axis_name, comm_dtype):
        self.axis_name = axis_name
        self.comm_dtype = comm_dtype


_EXPERT_STACK: List[_ExpertCtx] = []
_COLLECT_STACK: List["MoEMetrics"] = []


class MoEMetrics:
    """Per-trace accumulator the MoE cells append to."""

    def __init__(self):
        self.aux_losses = []
        self.dropped = []

    def add(self, aux):
        self.aux_losses.append(aux["aux_loss"])
        self.dropped.append(aux["dropped"])

    def aux_loss(self):
        return sum(self.aux_losses) if self.aux_losses else jnp.float32(0.0)

    def dropped_total(self):
        return sum(self.dropped) if self.dropped else jnp.int32(0)


@contextlib.contextmanager
def expert_axis(axis_name: str, comm_dtype: Optional[str] = None):
    """While active, MoE cells traced under this context dispatch with
    ``expert_parallel_moe`` over `axis_name` (their expert params are the
    local ep shards) instead of the single-shard ``moe_ffn``."""
    _EXPERT_STACK.append(_ExpertCtx(axis_name, comm_dtype))
    try:
        yield
    finally:
        _EXPERT_STACK.pop()


def current_expert_axis() -> Optional[_ExpertCtx]:
    return _EXPERT_STACK[-1] if _EXPERT_STACK else None


@contextlib.contextmanager
def collect_metrics():
    """Collect every MoE cell's (aux_loss, dropped) traced inside the
    ``with`` body. Yields the ``MoEMetrics`` accumulator."""
    mc = MoEMetrics()
    _COLLECT_STACK.append(mc)
    try:
        yield mc
    finally:
        _COLLECT_STACK.pop()


def report_metrics(aux):
    """Called by MoE cells after each gated forward."""
    if _COLLECT_STACK:
        _COLLECT_STACK[-1].add(aux)
