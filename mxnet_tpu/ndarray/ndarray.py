"""NDArray: imperative array with async semantics over jax.Array.

Reference: include/mxnet/ndarray.h (1486 l) + src/ndarray/ndarray.cc +
python/mxnet/ndarray/ndarray.py. TPU-native redesign (SURVEY.md §7):

  - the payload is an immutable `jax.Array`; "mutation" (+=, x[...]=v, out=)
    swaps the payload and bumps a version counter — this gives the reference's
    var-version semantics (engine.h:44-61) without a dependency engine, since
    XLA/PJRT already orders async work on its streams.
  - `wait_to_read()` == `block_until_ready()`; dispatch is async exactly like
    the reference engine's PushAsync, but scheduling is owned by PJRT.
  - every operator call routes through `invoke()` below: raw jax arrays in,
    compiled (jit-cached) op out, optional VJP recorded on the autograd tape.
"""
from __future__ import annotations

import functools
import time as _time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError, default_dtype
from ..context import Context, current_context
from ..ops.registry import Op, env, get_op, invoke_raw

__all__ = ["NDArray", "invoke", "array", "zeros", "ones", "full", "empty",
           "arange", "eye", "linspace", "concat", "stack", "waitall",
           "from_numpy", "from_jax"]


# ---------------------------------------------------------------------------
# waitall support: weak tracking of in-flight arrays (engine.WaitForAll parity)
# ---------------------------------------------------------------------------
import collections
import weakref

_INFLIGHT: collections.deque = collections.deque()
_INFLIGHT_CAP = 4096


def _track(arr: "NDArray"):
    # bounded without losing Engine::WaitForAll parity: on overflow the
    # OLDEST tracked arrays are synced before being dropped (they are the
    # most likely to be done already), never silently forgotten
    if len(_INFLIGHT) >= _INFLIGHT_CAP:
        # drop the oldest half, blocking only on genuinely incomplete
        # arrays — the oldest are overwhelmingly done already
        for _ in range(_INFLIGHT_CAP // 2):
            if not _INFLIGHT:
                break
            a = _INFLIGHT.popleft()()
            if a is not None:
                try:
                    if not a._data.is_ready():
                        a._data.block_until_ready()
                except Exception:
                    pass
    _INFLIGHT.append(weakref.ref(arr))


def waitall():
    """Block until all dispatched work completes (reference Engine::WaitForAll)."""
    while _INFLIGHT:
        ref = _INFLIGHT.pop()
        a = ref()
        if a is not None:
            try:
                a._data.block_until_ready()
            except Exception:
                pass
    jax.effects_barrier()


class NDArray:
    __slots__ = ("_data", "_ctx", "_version", "_grad", "_grad_req", "_ag_node",
                 "__weakref__")

    # numpy interop priority
    __array_priority__ = 1000.0

    def __init__(self, data, ctx: Optional[Context] = None):
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array):
            data = jnp.asarray(data)
        self._ctx = ctx or current_context()
        self._data = data
        self._version = 0
        self._grad: Optional[NDArray] = None
        self._grad_req = "null"
        self._ag_node = None

    # -- payload management -------------------------------------------------
    def _set_data(self, raw):
        self._data = raw
        self._version += 1

    @property
    def handle(self):  # API parity; the jax.Array IS the handle
        return self._data

    @property
    def version(self) -> int:
        return self._version

    # -- basic properties ----------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self) -> int:
        return int(self._data.size)

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def ctx(self) -> Context:
        return self._ctx

    context = ctx

    @property
    def stype(self) -> str:
        return "default"

    @property
    def T(self) -> "NDArray":
        return self.transpose()

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    # -- sync / transfer -----------------------------------------------------
    def wait_to_read(self):
        self._data.block_until_ready()

    def wait_to_write(self):
        self._data.block_until_ready()

    def asnumpy(self) -> _np.ndarray:
        # a writable COPY, reference semantics: on the CPU backend
        # np.asarray would alias the (immutable) device buffer and
        # surprise callers that mutate the result
        out = _np.asarray(self._data)
        if not out.flags.writeable:
            out = out.copy()
        return out

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("ambiguous truth value of multi-element NDArray")

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of 0-d array")
        return self.shape[0]

    def tolist(self):
        return self.asnumpy().tolist()

    def copy(self) -> "NDArray":
        return NDArray(self._data, self._ctx)

    def copyto(self, other: Union["NDArray", Context]) -> "NDArray":
        if isinstance(other, Context):
            return self.as_in_context(other)
        other._set_data(jax.device_put(self._data, other._ctx.jax_device)
                        .astype(other.dtype))
        return other

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self._ctx:
            return self
        out = NDArray(jax.device_put(self._data, ctx.jax_device), ctx)
        return out

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    def astype(self, dtype, copy=True) -> "NDArray":
        d = jnp.dtype(dtype)
        if not copy and d == self.dtype:
            return self
        return invoke("Cast", [self], {"dtype": str(d) if d != jnp.bfloat16 else "bfloat16"})

    def detach(self) -> "NDArray":
        out = NDArray(self._data, self._ctx)
        return out

    def tostype(self, stype):
        if stype != "default":
            from ..base import NotSupportedForSparseNDArray
            raise NotSupportedForSparseNDArray(
                "sparse storage is emulated; see mxnet_tpu.ndarray.sparse")
        return self

    # -- autograd ------------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype=None):
        from .. import autograd
        self._grad = zeros(self.shape, dtype=self.dtype, ctx=self._ctx)
        self._grad_req = grad_req
        autograd.mark_variables([self], [self._grad], grad_reqs=grad_req)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # -- repr ---------------------------------------------------------------
    def __repr__(self):
        return f"\n{self.asnumpy()}\n<NDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"

    # -- elementwise dunders -------------------------------------------------
    def _binary(self, other, op_name, scalar_op_name, reverse=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            return invoke(op_name, [a, b], {})
        if isinstance(other, (int, float, bool, _np.number)):
            name = scalar_op_name
            return invoke(name, [self], {"scalar": float(other)})
        if isinstance(other, _np.ndarray):
            o = NDArray(jnp.asarray(other), self._ctx)
            a, b = (o, self) if reverse else (self, o)
            return invoke(op_name, [a, b], {})
        return NotImplemented

    def __add__(self, o): return self._binary(o, "broadcast_add", "_plus_scalar")
    def __radd__(self, o): return self._binary(o, "broadcast_add", "_plus_scalar")
    def __sub__(self, o): return self._binary(o, "broadcast_sub", "_minus_scalar")
    def __rsub__(self, o): return self._binary(o, "broadcast_sub", "_rminus_scalar", reverse=True)
    def __mul__(self, o): return self._binary(o, "broadcast_mul", "_mul_scalar")
    def __rmul__(self, o): return self._binary(o, "broadcast_mul", "_mul_scalar")
    def __truediv__(self, o): return self._binary(o, "broadcast_div", "_div_scalar")
    def __rtruediv__(self, o): return self._binary(o, "broadcast_div", "_rdiv_scalar", reverse=True)
    def __mod__(self, o): return self._binary(o, "broadcast_mod", "_mod_scalar")
    def __rmod__(self, o): return self._binary(o, "broadcast_mod", "_rmod_scalar", reverse=True)
    def __pow__(self, o): return self._binary(o, "broadcast_power", "_power_scalar")
    def __rpow__(self, o): return self._binary(o, "broadcast_power", "_rpower_scalar", reverse=True)
    def __eq__(self, o):
        if o is None:
            return False
        return self._binary(o, "broadcast_equal", "_equal_scalar")
    def __ne__(self, o):
        if o is None:
            return True
        return self._binary(o, "broadcast_not_equal", "_not_equal_scalar")
    def __gt__(self, o): return self._binary(o, "broadcast_greater", "_greater_scalar")
    def __ge__(self, o): return self._binary(o, "broadcast_greater_equal", "_greater_equal_scalar")
    def __lt__(self, o): return self._binary(o, "broadcast_lesser", "_lesser_scalar")
    def __le__(self, o): return self._binary(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def __neg__(self): return invoke("negative", [self], {})
    def __abs__(self): return invoke("abs", [self], {})

    # in-place: swap payload (version bump == write dependency)
    def __iadd__(self, o):
        out = self.__add__(o)
        self._set_data(out._data)
        return self

    def __isub__(self, o):
        out = self.__sub__(o)
        self._set_data(out._data)
        return self

    def __imul__(self, o):
        out = self.__mul__(o)
        self._set_data(out._data)
        return self

    def __itruediv__(self, o):
        out = self.__truediv__(o)
        self._set_data(out._data)
        return self

    # -- indexing ------------------------------------------------------------
    def _norm_index(self, key):
        if isinstance(key, NDArray):
            return key._data
        if isinstance(key, tuple):
            return tuple(k._data if isinstance(k, NDArray) else k for k in key)
        return key

    def __getitem__(self, key):
        key = self._norm_index(key)
        if isinstance(key, (int, _np.integer)):
            # jnp CLAMPS out-of-range indices; python iteration relies on
            # IndexError to terminate (`for row in arr`), so check here
            n = self.shape[0] if self.ndim else 0
            if not -n <= key < n:
                raise IndexError(
                    f"index {int(key)} is out of bounds for axis 0 with "
                    f"size {n}")
        out_raw = self._data[key]
        out = NDArray(out_raw, self._ctx)
        # record slice on tape if needed
        from .. import autograd
        if autograd.is_recording() and self._ag_node is not None:
            def vjp_fn(cot, _key=key, _shape=self.shape, _dtype=self.dtype):
                z = jnp.zeros(_shape, _dtype)
                return (z.at[_key].add(cot),)
            autograd.record_op(vjp_fn, [self], [out], out_is_tuple=False,
                               refn=lambda a, _k=key: a[_k])
        _track(out)
        return out

    def __setitem__(self, key, value):
        key = self._norm_index(key)
        if isinstance(value, NDArray):
            value = value._data
        if isinstance(key, slice) and key == slice(None) and not isinstance(value, jax.Array):
            self._set_data(jnp.full(self.shape, value, self.dtype))
            return
        v = jnp.asarray(value, dtype=self.dtype) if not isinstance(value, jax.Array) else value.astype(self.dtype)
        self._set_data(self._data.at[key].set(v))

    # -- op-backed methods ---------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if not shape and "shape" in kwargs:
            shape = tuple(kwargs["shape"])
        return invoke("Reshape", [self], {"shape": shape,
                                          "reverse": kwargs.get("reverse", False)})

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def expand_dims(self, axis):
        return invoke("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None):
        return invoke("squeeze", [self], {"axis": axis})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return invoke("transpose", [self], {"axes": axes or None})

    def flatten(self):
        return invoke("Flatten", [self], {})

    def broadcast_to(self, shape):
        return invoke("broadcast_to", [self], {"shape": tuple(shape)})

    def broadcast_like(self, other):
        return invoke("broadcast_like", [self, other], {})

    def slice(self, begin, end, step=None):
        return invoke("slice", [self], {"begin": tuple(begin), "end": tuple(end),
                                        "step": tuple(step) if step else None})

    def slice_axis(self, axis, begin, end):
        return invoke("slice_axis", [self], {"axis": axis, "begin": begin, "end": end})

    def take(self, indices, axis=0, mode="clip"):
        return invoke("take", [self, indices], {"axis": axis, "mode": mode})

    def one_hot(self, depth, **kw):
        return invoke("one_hot", [self], {"depth": depth, **kw})

    def pick(self, index, axis=-1, keepdims=False):
        return invoke("pick", [self, index], {"axis": axis, "keepdims": keepdims})

    def clip(self, a_min, a_max):
        return invoke("clip", [self], {"a_min": a_min, "a_max": a_max})

    def abs(self): return invoke("abs", [self], {})
    def sign(self): return invoke("sign", [self], {})
    def sqrt(self): return invoke("sqrt", [self], {})
    def square(self): return invoke("square", [self], {})
    def exp(self): return invoke("exp", [self], {})
    def log(self): return invoke("log", [self], {})
    def relu(self): return invoke("relu", [self], {})
    def sigmoid(self): return invoke("sigmoid", [self], {})
    def tanh(self): return invoke("tanh", [self], {})
    def softmax(self, axis=-1): return invoke("softmax", [self], {"axis": axis})
    def log_softmax(self, axis=-1): return invoke("log_softmax", [self], {"axis": axis})
    def round(self): return invoke("round", [self], {})
    def floor(self): return invoke("floor", [self], {})
    def ceil(self): return invoke("ceil", [self], {})

    def _reduce(self, name, axis=None, keepdims=False, **kw):
        return invoke(name, [self], {"axis": axis, "keepdims": keepdims, **kw})

    def sum(self, axis=None, keepdims=False):
        return self._reduce("sum", axis, keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._reduce("mean", axis, keepdims)

    def prod(self, axis=None, keepdims=False):
        return self._reduce("prod", axis, keepdims)

    def max(self, axis=None, keepdims=False):
        return self._reduce("max", axis, keepdims)

    def min(self, axis=None, keepdims=False):
        return self._reduce("min", axis, keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        return invoke("norm", [self], {"ord": ord, "axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False):
        return invoke("argmax", [self], {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        return invoke("argmin", [self], {"axis": axis, "keepdims": keepdims})

    def argsort(self, axis=-1, is_ascend=True):
        return invoke("argsort", [self], {"axis": axis, "is_ascend": is_ascend})

    def sort(self, axis=-1, is_ascend=True):
        return invoke("sort", [self], {"axis": axis, "is_ascend": is_ascend})

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return invoke("topk", [self], {"axis": axis, "k": k, "ret_typ": ret_typ,
                                       "is_ascend": is_ascend})

    def dot(self, other, transpose_a=False, transpose_b=False):
        return invoke("dot", [self, other], {"transpose_a": transpose_a,
                                             "transpose_b": transpose_b})

    def zeros_like(self): return invoke("zeros_like", [self], {})
    def ones_like(self): return invoke("ones_like", [self], {})

    def tile(self, reps): return invoke("tile", [self], {"reps": tuple(reps)})

    def repeat(self, repeats, axis=None):
        return invoke("repeat", [self], {"repeats": repeats, "axis": axis})

    def flip(self, axis): return invoke("reverse", [self], {"axis": axis})

    def swapaxes(self, dim1, dim2):
        return invoke("swapaxes", [self], {"dim1": dim1, "dim2": dim2})

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return invoke("SliceChannel", [self], {"num_outputs": num_outputs,
                                               "axis": axis,
                                               "squeeze_axis": squeeze_axis})

    # dlpack / numpy protocols
    def __dlpack__(self, stream=None):
        return self._data.__dlpack__(stream=stream)

    def __dlpack_device__(self):
        return self._data.__dlpack_device__()

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype else a


def _wrap_like(raw, like: NDArray) -> NDArray:
    return NDArray(raw, like._ctx)


# ---------------------------------------------------------------------------
# Eager dispatch
# ---------------------------------------------------------------------------

def invoke(op: Union[str, Op], inputs: Sequence[NDArray], params: Dict[str, Any],
           out: Optional[Union[NDArray, Sequence[NDArray]]] = None):
    """The imperative path (reference MXImperativeInvokeEx →
    Imperative::Invoke, SURVEY.md §3.1 — here it is a jit-cache lookup)."""
    if isinstance(op, str):
        op = get_op(op)
    params = {k: v for k, v in params.items() if v is not None} if None in params.values() else params
    raw = [x._data for x in inputs]
    from .. import autograd
    need_grad = (op.differentiable and autograd.is_recording()
                 and any(x._ag_node is not None for x in inputs))
    vjp_fn = None
    was_tuple = False
    from ..ops import registry as _reg
    _plat = _reg._platform_of(raw)
    _tok = _reg.exec_platform.set(_plat) if _plat is not None else None
    _ph = _reg._profile_hook
    _t0 = _time.perf_counter() if _ph is not None else 0.0
    try:
        if need_grad:
            # vjp over the unjitted fn: linearizing through an inner pjit
            # breaks for some primitives (reduce_window_max) on this jax
            # version
            outs_raw, vjp_fn = jax.vjp(op.unbound(params), *raw)
        else:
            outs_raw = op(*raw, **params)
    finally:
        if _tok is not None:
            _reg.exec_platform.reset(_tok)
    if _ph is not None:
        _ph(op.name, _t0, _time.perf_counter())
    if isinstance(outs_raw, tuple):
        was_tuple = True
    else:
        outs_raw = (outs_raw,)
    if env.get("MXNET_ENGINE_TYPE") == "Naive":
        jax.block_until_ready(outs_raw)
    spec = op.state_inputs
    if spec is not None:
        # optimizer-style ops: updated states are trailing outputs written
        # back into their input arrays (the reference mutates them in place)
        pairs = spec(raw, params) if callable(spec) else spec
        state_out = set()
        for in_idx, out_idx in pairs:
            inputs[in_idx]._set_data(outs_raw[out_idx])
            state_out.add(out_idx)
        outs_raw = tuple(o for i, o in enumerate(outs_raw)
                         if i not in state_out)
        if len(outs_raw) == 1:
            was_tuple = False
    ctx = inputs[0]._ctx if inputs else current_context()
    outs = [NDArray(o, ctx) for o in outs_raw]
    for o in outs:
        _track(o)
    if need_grad:
        autograd.record_op(vjp_fn, list(inputs), outs, out_is_tuple=was_tuple,
                           refn=op.unbound(params))
    if out is not None:
        targets = [out] if isinstance(out, NDArray) else list(out)
        for t, o in zip(targets, outs):
            t._set_data(o._data)
        return out
    if len(outs) == 1 and not was_tuple:
        return outs[0]
    return outs


# ---------------------------------------------------------------------------
# Creation functions (reference src/operator/tensor/init_op.cc + ndarray.py)
# ---------------------------------------------------------------------------

def _ctx_dev(ctx):
    ctx = ctx or current_context()
    return ctx, ctx.jax_device


def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    if isinstance(source, NDArray):
        source = source._data
    ctx, dev = _ctx_dev(ctx)
    if dtype is None and not isinstance(source, jax.Array):
        probe = source if isinstance(source, _np.ndarray) else _np.asarray(source)
        # jax runs x64-disabled: f64 sources land as the default dtype (f32)
        dtype = default_dtype() if probe.dtype == _np.float64 else probe.dtype
        source = probe
    raw = jax.device_put(jnp.asarray(source, dtype=dtype), dev)
    return NDArray(raw, ctx)


def from_numpy(a: _np.ndarray, ctx=None) -> NDArray:
    return array(a, ctx=ctx)


def from_jax(a: jax.Array, ctx=None) -> NDArray:
    return NDArray(a, ctx or current_context())


def zeros(shape, ctx=None, dtype=None) -> NDArray:
    ctx, dev = _ctx_dev(ctx)
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(jax.device_put(jnp.zeros(shape, dtype or default_dtype()), dev), ctx)


def ones(shape, ctx=None, dtype=None) -> NDArray:
    ctx, dev = _ctx_dev(ctx)
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(jax.device_put(jnp.ones(shape, dtype or default_dtype()), dev), ctx)


def full(shape, val, ctx=None, dtype=None) -> NDArray:
    ctx, dev = _ctx_dev(ctx)
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(jax.device_put(jnp.full(shape, val, dtype or default_dtype()), dev), ctx)


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None) -> NDArray:
    ctx, dev = _ctx_dev(ctx)
    raw = jnp.arange(start, stop, step, dtype=dtype or default_dtype())
    if repeat > 1:
        raw = jnp.repeat(raw, repeat)
    return NDArray(jax.device_put(raw, dev), ctx)


def eye(N, M=0, k=0, ctx=None, dtype=None) -> NDArray:
    ctx, dev = _ctx_dev(ctx)
    raw = jnp.eye(N, M if M else N, k=k, dtype=dtype or default_dtype())
    return NDArray(jax.device_put(raw, dev), ctx)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None) -> NDArray:
    ctx, dev = _ctx_dev(ctx)
    raw = jnp.linspace(start, stop, num, endpoint=endpoint, dtype=dtype or default_dtype())
    return NDArray(jax.device_put(raw, dev), ctx)


def concat(*arrays, dim=1):
    return invoke("Concat", list(arrays), {"dim": dim})


def stack(*arrays, axis=0):
    return invoke("stack", list(arrays), {"axis": axis})
