"""`mx.nd.linalg` namespace (reference python/mxnet/ndarray/linalg.py)."""
from __future__ import annotations

from .ndarray import invoke


def gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0, axis=-2):
    return invoke("linalg_gemm", [A, B, C], dict(transpose_a=transpose_a,
                  transpose_b=transpose_b, alpha=alpha, beta=beta))


def gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    return invoke("linalg_gemm2", [A, B], dict(transpose_a=transpose_a,
                  transpose_b=transpose_b, alpha=alpha))


def potrf(A):
    return invoke("linalg_potrf", [A], {})


def potri(A):
    return invoke("linalg_potri", [A], {})


def trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    return invoke("linalg_trsm", [A, B], dict(transpose=transpose,
                  rightside=rightside, lower=lower, alpha=alpha))


def trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    return invoke("linalg_trmm", [A, B], dict(transpose=transpose,
                  rightside=rightside, lower=lower, alpha=alpha))


def sumlogdiag(A):
    return invoke("linalg_sumlogdiag", [A], {})


def syrk(A, transpose=False, alpha=1.0):
    return invoke("linalg_syrk", [A], dict(transpose=transpose, alpha=alpha))


def extractdiag(A, offset=0):
    return invoke("linalg_extractdiag", [A], dict(offset=offset))


def makediag(A, offset=0):
    return invoke("linalg_makediag", [A], dict(offset=offset))


def gelqf(A):
    return invoke("linalg_gelqf", [A], {})


def inverse(A):
    return invoke("linalg_inverse", [A], {})


def det(A):
    return invoke("linalg_det", [A], {})


def slogdet(A):
    return invoke("linalg_slogdet", [A], {})


def syevd(A):
    return invoke("linalg_syevd", [A], {})
