"""mx.nd.image — image op namespace (reference python/mxnet/ndarray/image.py):
`nd.image.to_tensor/normalize/crop/resize/flip_*` over the `_image_*`
registered ops."""
from __future__ import annotations

from ..base import MXNetError
from ..ops.registry import get_op as _get_op


def __getattr__(name):
    from . import _make_wrapper
    for cand in (f"_image_{name}", name):
        try:
            _get_op(cand)
        except MXNetError:
            continue
        fn = _make_wrapper(cand)
        globals()[name] = fn
        return fn
    raise AttributeError(
        f"module 'mxnet_tpu.ndarray.image' has no attribute '{name}'")
