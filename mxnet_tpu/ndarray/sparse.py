"""Sparse NDArray emulation (reference python/mxnet/ndarray/sparse.py,
include/mxnet/ndarray.h storage types kRowSparseStorage/kCSRStorage).

XLA has no dynamic sparsity, so these are *dense-backed* views that preserve
the reference API (`.indices`, `.data`, `.tostype`, `row_sparse_array`,
`csr_matrix`) with documented semantic deltas (SURVEY.md §7 hard-part 4):
storage is dense on device; `indices` are recovered by scanning. Sparse
*gradients* for embeddings are instead handled natively by XLA scatter in the
optimizer path, which is the part that matters for performance.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as _np

from ..context import current_context
from .ndarray import NDArray, array, zeros


class BaseSparseNDArray(NDArray):
    __slots__ = ()


class RowSparseNDArray(BaseSparseNDArray):
    """Dense-backed row_sparse array."""
    __slots__ = ()

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self) -> NDArray:
        nz = _np.nonzero(_np.any(self.asnumpy().reshape(self.shape[0], -1) != 0, axis=1))[0]
        return array(nz.astype(_np.int64), ctx=self.ctx, dtype="int64")

    @property
    def data(self) -> NDArray:
        idx = self.indices.asnumpy().astype(int)
        return array(self.asnumpy()[idx], ctx=self.ctx)

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data, self._ctx)
        if stype == "row_sparse":
            return self
        raise ValueError(stype)


class CSRNDArray(BaseSparseNDArray):
    __slots__ = ()

    @property
    def stype(self):
        return "csr"

    @property
    def indices(self) -> NDArray:
        import scipy.sparse as sp
        m = sp.csr_matrix(self.asnumpy())
        return array(m.indices.astype(_np.int64), ctx=self.ctx, dtype="int64")

    @property
    def indptr(self) -> NDArray:
        import scipy.sparse as sp
        m = sp.csr_matrix(self.asnumpy())
        return array(m.indptr.astype(_np.int64), ctx=self.ctx, dtype="int64")

    @property
    def data(self) -> NDArray:
        import scipy.sparse as sp
        m = sp.csr_matrix(self.asnumpy())
        return array(m.data, ctx=self.ctx)

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data, self._ctx)
        if stype == "csr":
            return self
        raise ValueError(stype)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Build a row_sparse array from (data, indices) or dense source."""
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = _np.asarray(data.asnumpy() if isinstance(data, NDArray) else data)
        indices = _np.asarray(indices.asnumpy() if isinstance(indices, NDArray) else indices).astype(int)
        if shape is None:
            nrows = int(indices.max()) + 1 if indices.size else 0
            shape = (nrows,) + data.shape[1:]
        dense = _np.zeros(shape, dtype=dtype or data.dtype)
        dense[indices] = data
        return RowSparseNDArray(jnp.asarray(dense), ctx)
    src = arg1.asnumpy() if isinstance(arg1, NDArray) else _np.asarray(arg1)
    return RowSparseNDArray(jnp.asarray(src, dtype=dtype), ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = (
            _np.asarray(x.asnumpy() if isinstance(x, NDArray) else x) for x in arg1)
        import scipy.sparse as sp
        m = sp.csr_matrix((data, indices.astype(int), indptr.astype(int)), shape=shape)
        return CSRNDArray(jnp.asarray(m.toarray(), dtype=dtype), ctx)
    src = arg1.asnumpy() if isinstance(arg1, NDArray) else _np.asarray(arg1)
    return CSRNDArray(jnp.asarray(src, dtype=dtype), ctx)


def zeros_sparse(stype, shape, ctx=None, dtype=None):
    z = zeros(shape, ctx=ctx, dtype=dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(z._data, z.ctx)
    if stype == "csr":
        return CSRNDArray(z._data, z.ctx)
    return z
