"""mx.nd.contrib — contrib op namespace (reference
python/mxnet/ndarray/contrib.py): compiled control flow (foreach,
while_loop, cond) plus every `_contrib_*` registered op without the prefix.
"""
from __future__ import annotations

from ..ops.control_flow import foreach, while_loop, cond  # noqa: F401
from ..ops.registry import all_ops as _all_ops, get_op as _get_op
from ..base import MXNetError


# isnan/isinf/isfinite resolve through __getattr__ to the registered
# _contrib_is* ops — one definition serving nd, sym, and jit paths


def rand_zipfian(true_classes, num_sampled, range_max, ctx=None):
    """Log-uniform (Zipfian) candidate sampler (reference
    ndarray/contrib.py:40): draws num_sampled candidates with replacement
    from P(class) = (log(class+2) - log(class+1)) / log(range_max+1) and
    returns (samples, expected_count_true, expected_count_sampled) — the
    NCE/sampled-softmax helper for frequency-sorted vocabularies."""
    import math as _math
    from .random import uniform

    log_range = _math.log(range_max + 1)
    rand = uniform(0, log_range, shape=(num_sampled,), ctx=ctx)
    # int32 under the x32 policy (reference returns int64)
    sampled = (rand.exp() - 1.0).astype("int32") % range_max

    def _expected(cls_float):
        return ((cls_float + 2.0) / (cls_float + 1.0)).log() \
            / log_range * num_sampled

    true_f = true_classes.astype("float32")
    expected_true = _expected(true_f)
    expected_sampled = _expected(sampled.astype("float32"))
    return sampled, expected_true, expected_sampled


def __getattr__(name):
    """`mx.nd.contrib.box_nms` -> registered op `_contrib_box_nms` (or the
    bare name), wrapped for NDArray in/out via the nd namespace."""
    from . import _make_wrapper
    for cand in (f"_contrib_{name}", name):
        try:
            _get_op(cand)
        except MXNetError:
            continue
        fn = _make_wrapper(cand)
        globals()[name] = fn
        return fn
    raise AttributeError(f"module 'mxnet_tpu.ndarray.contrib' has no "
                         f"attribute '{name}'")
