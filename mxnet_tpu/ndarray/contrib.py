"""mx.nd.contrib — contrib op namespace (reference
python/mxnet/ndarray/contrib.py): compiled control flow (foreach,
while_loop, cond) plus every `_contrib_*` registered op without the prefix.
"""
from __future__ import annotations

from ..ops.control_flow import foreach, while_loop, cond  # noqa: F401
from ..ops.registry import all_ops as _all_ops, get_op as _get_op
from ..base import MXNetError


def isfinite(data):
    from . import NDArray
    import jax.numpy as jnp
    raw = data._data if isinstance(data, NDArray) else data
    return NDArray(jnp.isfinite(raw).astype(jnp.float32))


def __getattr__(name):
    """`mx.nd.contrib.box_nms` -> registered op `_contrib_box_nms` (or the
    bare name), wrapped for NDArray in/out via the nd namespace."""
    from . import _make_wrapper
    for cand in (f"_contrib_{name}", name):
        try:
            _get_op(cand)
        except MXNetError:
            continue
        fn = _make_wrapper(cand)
        globals()[name] = fn
        return fn
    raise AttributeError(f"module 'mxnet_tpu.ndarray.contrib' has no "
                         f"attribute '{name}'")
