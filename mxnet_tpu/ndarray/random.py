"""`mx.nd.random` — sampling functions (reference src/operator/random/sample_op.cc,
python/mxnet/ndarray/random.py). Counter-based threefry keys under the hood."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import default_dtype
from ..context import current_context
from .. import random as _rng
from .ndarray import NDArray, _track


def _make(raw, ctx):
    ctx = ctx or current_context()
    out = NDArray(jax.device_put(raw, ctx.jax_device), ctx)
    _track(out)
    return out


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)



def _sample_op(op_name, params, shape, dtype, out=None):
    """Array-parameterized draw (reference python/mxnet/ndarray/random.py
    _random_helper: NDArray params dispatch to _sample_<dist>). Routes
    through the generated nd wrapper so the RNG-key feeding lives in ONE
    place (_RNG_SAMPLE_OPS in ndarray/__init__.py)."""
    import importlib
    from .ndarray import array as _array
    nd_mod = importlib.import_module("mxnet_tpu.ndarray")
    nds = [pv if isinstance(pv, NDArray) else _array(pv) for pv in params]
    return getattr(nd_mod, op_name)(
        *nds, shape=shape, dtype=dtype or str(default_dtype()), out=out)


def uniform(low=0.0, high=1.0, shape=None, dtype=None, ctx=None, out=None):
    if isinstance(low, NDArray) or isinstance(high, NDArray):
        return _sample_op("_sample_uniform", [low, high], shape, dtype,
                          out=out)
    dtype = dtype or default_dtype()
    raw = jax.random.uniform(_rng.next_key(), _shape(shape), dtype=jnp.float32,
                             minval=low, maxval=high).astype(dtype)
    r = _make(raw, ctx)
    if out is not None:
        out._set_data(r._data)
        return out
    return r


def normal(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None):
    if isinstance(loc, NDArray) or isinstance(scale, NDArray):
        return _sample_op("_sample_normal", [loc, scale], shape, dtype,
                          out=out)
    dtype = dtype or default_dtype()
    raw = loc + scale * jax.random.normal(_rng.next_key(), _shape(shape), dtype=jnp.float32)
    r = _make(raw.astype(dtype), ctx)
    if out is not None:
        out._set_data(r._data)
        return out
    return r


def randn(*shape, loc=0.0, scale=1.0, dtype=None, ctx=None, out=None):
    """reference ndarray/random.py:170 randn(*shape, loc=, scale=): the
    shape is POSITIONAL — `randn(2, 3)` draws a (2, 3) standard normal
    (an alias to `normal` here would silently read loc=2, scale=3)."""
    return normal(loc=loc, scale=scale, shape=shape or None, dtype=dtype,
                  ctx=ctx, out=out)


def randint(low, high=None, shape=None, dtype="int32", ctx=None):
    if high is None:
        low, high = 0, low
    raw = jax.random.randint(_rng.next_key(), _shape(shape), low, high,
                             dtype=jnp.dtype(dtype))
    return _make(raw, ctx)


def poisson(lam=1.0, shape=None, dtype=None, ctx=None):
    if isinstance(lam, NDArray):
        return _sample_op("_sample_poisson", [lam], shape, dtype)
    raw = jax.random.poisson(_rng.next_key(), lam, _shape(shape))
    return _make(raw.astype(dtype or default_dtype()), ctx)


def exponential(scale=1.0, shape=None, dtype=None, ctx=None):
    if isinstance(scale, NDArray):
        # the multisample op takes the RATE lam = 1/scale (reference
        # random.py exponential -> _sample_exponential(1/scale))
        return _sample_op("_sample_exponential", [1.0 / scale], shape, dtype)
    raw = scale * jax.random.exponential(_rng.next_key(), _shape(shape))
    return _make(raw.astype(dtype or default_dtype()), ctx)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype=None, ctx=None):
    if isinstance(alpha, NDArray) or isinstance(beta, NDArray):
        return _sample_op("_sample_gamma", [alpha, beta], shape, dtype)
    raw = beta * jax.random.gamma(_rng.next_key(), alpha, _shape(shape))
    return _make(raw.astype(dtype or default_dtype()), ctx)


def negative_binomial(k=1, p=1, shape=None, dtype=None, ctx=None):
    if isinstance(k, NDArray) or isinstance(p, NDArray):
        return _sample_op("_sample_negative_binomial", [k, p], shape, dtype)
    g = jax.random.gamma(_rng.next_key(), k, _shape(shape)) * (1 - p) / p
    raw = jax.random.poisson(_rng.next_key(), g, _shape(shape))
    return _make(raw.astype(dtype or default_dtype()), ctx)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype=None, ctx=None):
    if isinstance(mu, NDArray) or isinstance(alpha, NDArray):
        return _sample_op("_sample_generalized_negative_binomial",
                          [mu, alpha], shape, dtype)
    r = 1.0 / alpha
    p = r / (r + mu)
    return negative_binomial(r, p, shape, dtype, ctx)


def multinomial(data, shape=None, get_prob=False, dtype="int32"):
    """Sample category indices from probability rows (reference sample_multinomial_op)."""
    logits = jnp.log(jnp.maximum(data._data, 1e-30))
    n = 1 if shape is None else (shape if isinstance(shape, int) else int(jnp.prod(jnp.asarray(shape))))
    if logits.ndim == 1:
        out = jax.random.categorical(_rng.next_key(), logits, shape=(n,))
        if shape is None:
            out = out[0]
    else:
        out = jax.random.categorical(_rng.next_key(), logits, axis=-1,
                                     shape=(n, logits.shape[0])).T
        if shape is None:
            out = out[:, 0]
    res = _make(out.astype(jnp.dtype(dtype)), data.ctx)
    if get_prob:
        lp = jnp.take_along_axis(jax.nn.log_softmax(logits, axis=-1),
                                 jnp.atleast_2d(out.astype(jnp.int32)), axis=-1)
        return res, _make(lp, data.ctx)
    return res


def shuffle(data):
    idx = jax.random.permutation(_rng.next_key(), data.shape[0])
    return _make(jnp.take(data._data, idx, axis=0), data.ctx)


def bernoulli(p=0.5, shape=None, dtype=None, ctx=None):
    raw = jax.random.bernoulli(_rng.next_key(), p, _shape(shape))
    return _make(raw.astype(dtype or default_dtype()), ctx)
