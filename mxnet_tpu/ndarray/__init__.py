"""`mx.nd` namespace: NDArray + creation functions + every registered op.

Replaces the reference's import-time ctypes codegen
(python/mxnet/ndarray/register.py:116-271) with PEP-562 lazy wrappers over the
op registry — same surface (`nd.Convolution(data, w, b, kernel=(3,3), ...)`),
no C ABI.
"""
from __future__ import annotations

from typing import Optional

from ..ops.registry import all_ops, get_op
from .ndarray import (NDArray, invoke, array, zeros, ones, full, empty, arange,
                      eye, linspace, concat, stack, waitall, from_numpy, from_jax,
                      _wrap_like)
from . import random  # noqa: F401
from . import linalg  # noqa: F401
from . import sparse  # noqa: F401

__all__ = ["NDArray", "invoke", "array", "zeros", "ones", "full", "empty",
           "arange", "eye", "linspace", "concat", "stack", "waitall", "random",
           "linalg", "sparse"]


def zeros_like(a):
    return invoke("zeros_like", [a], {})


def ones_like(a):
    return invoke("ones_like", [a], {})


def maximum(lhs, rhs):
    """Elementwise max with scalar/array dispatch (reference
    python/mxnet/ndarray/ndarray.py maximum(): `_maximum` for two arrays,
    `_maximum_scalar` when one side is a python scalar)."""
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return invoke("_maximum", [lhs, rhs], {})
    if isinstance(lhs, NDArray):
        return invoke("_maximum_scalar", [lhs], {"scalar": float(rhs)})
    if isinstance(rhs, NDArray):
        return invoke("_maximum_scalar", [rhs], {"scalar": float(lhs)})
    return max(lhs, rhs)


def minimum(lhs, rhs):
    """Elementwise min with scalar/array dispatch (reference
    python/mxnet/ndarray/ndarray.py minimum())."""
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return invoke("_minimum", [lhs, rhs], {})
    if isinstance(lhs, NDArray):
        return invoke("_minimum_scalar", [lhs], {"scalar": float(rhs)})
    if isinstance(rhs, NDArray):
        return invoke("_minimum_scalar", [rhs], {"scalar": float(lhs)})
    return min(lhs, rhs)


def cast_storage(arr, stype):
    """Dense <-> sparse storage conversion (reference
    src/operator/tensor/cast_storage.cc). Sparse is dense-backed here, so
    this wraps/unwraps the CSR/RowSparse NDArray classes without copying."""
    if stype in ("default", None):
        if type(arr) is not NDArray:
            return NDArray(arr._data, arr._ctx)
        return arr
    from .sparse import CSRNDArray, RowSparseNDArray
    cls = {"csr": CSRNDArray, "row_sparse": RowSparseNDArray}[stype]
    if isinstance(arr, cls):
        return arr
    return cls._from_dense(arr) if hasattr(cls, "_from_dense") else cls(arr._data, arr._ctx)


def save(fname, data):
    from ..serialization import save_ndarrays
    save_ndarrays(fname, data)


def load(fname):
    from ..serialization import load_ndarrays
    return load_ndarrays(fname)


_SPECIAL_KEY_OPS = {"Dropout"}

# random sampling ops: the trailing `key` input is auto-created as an RNG
# variable in symbol graphs; eager calls draw one from the global stream
# here (reference-compatible imperative surface: nd.random_uniform(...),
# nd.sample_multinomial(probs), ...)
_RNG_SAMPLE_OPS = {"_random_uniform", "_random_normal",
                   "_random_uniform_like", "_random_normal_like",
                   "_sample_multinomial", "_sample_uniform",
                   "_sample_normal", "_sample_gamma",
                   "_sample_exponential", "_sample_poisson",
                   "_sample_negative_binomial",
                   "_sample_generalized_negative_binomial"}

# Derived ops for tensor-valued KEYWORD arguments (e.g.
# nd.CTCLoss(..., label_lengths=arr)): the reference treats these as
# tensor inputs, so they must ride the traced-input path — leaving them
# in params would hand the op an NDArray as a static argument (unhashable
# for the jit cache, invisible to autograd). Cached per (op, kw-names).
_KW_TENSOR_OPS = {}


def _kw_tensor_op(op, kw_names):
    key = (op.name, kw_names)
    cached = _KW_TENSOR_OPS.get(key)
    if cached is None:
        from ..ops.registry import Op
        base = op.fn
        n = len(kw_names)

        def fn(*arrs, **params):
            main, extra = arrs[:-n], arrs[-n:]
            return base(*main, **dict(zip(kw_names, extra)), **params)

        cached = Op(f"{op.name}<{','.join(kw_names)}>", fn,
                    differentiable=op.differentiable,
                    multi_output=op.multi_output)
        _KW_TENSOR_OPS[key] = cached
    return cached


def _make_wrapper(op_name: str):
    op = get_op(op_name)

    def wrapper(*args, out=None, **kwargs):
        inputs = []
        for a in args:
            if isinstance(a, NDArray):
                inputs.append(a)
            elif a is None:
                continue
            else:
                # allow raw numpy/list positional data
                inputs.append(array(a))
        if op.name in _SPECIAL_KEY_OPS:
            from .. import autograd as _ag
            from .. import random as _rnd
            kwargs.setdefault("training", _ag.is_training() or _ag.is_recording())
            if kwargs.get("training") and kwargs.get("p", 0.5) > 0 and len(inputs) == 1:
                inputs.append(NDArray(_rnd.next_key_raw()))
            elif len(inputs) == 1:
                import jax.numpy as jnp
                inputs.append(NDArray(jnp.zeros((2,), jnp.uint32)))
        elif op.name in _RNG_SAMPLE_OPS:
            # ride the tensor-kwarg path: a positional append would bind
            # the key to `data` when the caller passed data= by keyword
            from .. import random as _rnd
            kwargs["key"] = NDArray(_rnd.next_key_raw())
        nd_kw = {k: v for k, v in kwargs.items() if isinstance(v, NDArray)}
        if nd_kw:
            names = tuple(sorted(nd_kw))
            for k in names:
                kwargs.pop(k)
            inputs.extend(nd_kw[k] for k in names)
            return invoke(_kw_tensor_op(op, names), inputs, kwargs, out=out)
        return invoke(op, inputs, kwargs, out=out)

    wrapper.__name__ = op_name
    wrapper.__doc__ = op.doc
    return wrapper


_wrapper_cache = {}


def __getattr__(name: str):
    if name.startswith("__"):
        raise AttributeError(name)
    if name in _wrapper_cache:
        return _wrapper_cache[name]
    if name in ("contrib", "image"):
        # importlib, not `from . import`: the latter's hasattr() probe
        # re-enters this __getattr__ before the submodule import starts.
        import importlib
        return importlib.import_module(__name__ + "." + name)
    if name == "Custom":
        from ..operator import custom as _custom
        _wrapper_cache[name] = _custom
        return _custom
    try:
        get_op(name)
    except Exception:
        raise AttributeError(f"module 'mxnet_tpu.ndarray' has no attribute '{name}'") from None
    w = _make_wrapper(name)
    _wrapper_cache[name] = w
    return w


def __dir__():
    import sys
    mod = sys.modules[__name__]
    return sorted(set(list(mod.__dict__) + list(all_ops().keys())))
