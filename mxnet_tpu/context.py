"""Device contexts.

Replaces the reference's `Context` (include/mxnet/base.h:104-108, python/mxnet/context.py)
with a TPU-first design: a Context names a logical device (`tpu(i)`, `cpu(0)`) and maps
onto a concrete jax.Device. `gpu(i)` is accepted as an alias of `tpu(i)` so reference
scripts that say `ctx=mx.gpu(0)` keep working.

Unlike the reference there is no per-context stream/worker machinery here — XLA/PJRT
owns async dispatch (SURVEY.md section 7 mapping table).
"""
from __future__ import annotations

import threading
from typing import List, Optional

import jax


class Context:
    """A logical device. devtype in {'cpu', 'tpu'}; 'gpu' aliases 'tpu'."""

    _default_ctx = threading.local()

    devtype2id = {"cpu": 1, "gpu": 2, "tpu": 2, "cpu_pinned": 3, "cpu_shared": 5}
    devid2type = {1: "cpu", 2: "tpu", 3: "cpu_pinned", 5: "cpu_shared"}

    def __init__(self, device_type: str, device_id: int = 0):
        if device_type == "gpu":  # alias: accelerator == TPU in this framework
            device_type = "tpu"
        if device_type in ("cpu_pinned", "cpu_shared"):
            device_type = "cpu"
        if device_type not in ("cpu", "tpu"):
            raise ValueError(f"unknown device type {device_type}")
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- identity ----------------------------------------------------------
    @property
    def device_typeid(self) -> int:
        return self.devtype2id[self.device_type]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- jax mapping -------------------------------------------------------
    @property
    def jax_device(self) -> jax.Device:
        devs = _devices_of(self.device_type)
        if not devs:
            # graceful fallback: tpu requested but only cpu present (or vice versa)
            devs = jax.local_devices()
        return devs[self.device_id % len(devs)]

    def empty_cache(self):
        """Parity with mx.Context.empty_cache; XLA manages pools itself."""
        return None

    # -- default-context stack (with ctx: ...) -----------------------------
    def __enter__(self):
        stack = _ctx_stack()
        stack.append(self)
        return self

    def __exit__(self, *exc):
        _ctx_stack().pop()

    @classmethod
    def default_ctx(cls) -> "Context":
        stack = _ctx_stack()
        if stack:
            return stack[-1]
        return _initial_default_ctx()


def _ctx_stack() -> List[Context]:
    st = getattr(Context._default_ctx, "stack", None)
    if st is None:
        st = []
        Context._default_ctx.stack = st
    return st


_dev_cache = {}


def _devices_of(kind: str):
    if kind not in _dev_cache:
        # local_devices, not devices: in a multi-process (jax.distributed)
        # job the global list contains other workers' non-addressable
        # devices — Context must only ever resolve to a local one
        if kind == "cpu":
            try:
                _dev_cache[kind] = jax.local_devices(backend="cpu")
            except RuntimeError:
                _dev_cache[kind] = []
        else:
            # Any accelerator backend counts as "tpu" (axon tunnels report
            # platform-specific names; default backend is the accelerator).
            # A broken accelerator runtime (e.g. libtpu version mismatch)
            # must degrade to "no accelerator" so the default context falls
            # back to cpu(0) instead of crashing every eager op.
            try:
                devs = [d for d in jax.local_devices() if d.platform != "cpu"]
            except Exception as e:
                import warnings
                warnings.warn(
                    f"accelerator device enumeration failed ({e!r}); "
                    "falling back to cpu — training will run on the host CPU",
                    RuntimeWarning, stacklevel=3)
                devs = []
            _dev_cache[kind] = devs
    return _dev_cache[kind]


_INITIAL_DEFAULT = None


def _initial_default_ctx() -> Context:
    global _INITIAL_DEFAULT
    if _INITIAL_DEFAULT is None:
        _INITIAL_DEFAULT = tpu(0) if _devices_of("tpu") else cpu(0)
    return _INITIAL_DEFAULT


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Alias of tpu() — keeps reference scripts (`ctx=mx.gpu()`) running."""
    return Context("tpu", device_id)


def num_gpus() -> int:
    return len(_devices_of("tpu"))


def num_tpus() -> int:
    return len(_devices_of("tpu"))


def current_context() -> Context:
    return Context.default_ctx()
