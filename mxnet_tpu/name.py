"""Name management (reference python/mxnet/name.py): NameManager auto-names
symbols; Prefix prepends a scope prefix. Thread-local stack, used as

    with mx.name.Prefix("stage1_"):
        fc = mx.sym.FullyConnected(data, num_hidden=10)   # stage1_fullyconnected0
"""
from __future__ import annotations

import threading

_state = threading.local()


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = [NameManager()]
    return _state.stack


def current():
    return _stack()[-1]


class NameManager:
    """Auto-naming by per-hint counters (reference name.py NameManager)."""

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name is not None:
            return name
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return f"{hint}{idx}"

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()


class Prefix(NameManager):
    """Prepends `prefix` to every auto name (reference name.py Prefix)."""

    def __init__(self, prefix: str):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)
