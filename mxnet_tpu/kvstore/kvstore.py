"""KVStore facade (reference include/mxnet/kvstore.h:105-438, src/kvstore/*).

SURVEY.md §5-h: the reference's four comm paths (in-process Comm trees, NCCL,
ps-lite parameter server, Horovod) all collapse on TPU into XLA collectives
over the device mesh. This module keeps the push/pull API for compatibility:

  - 'local' / 'device' / 'tpu': single-process store. With multiple devices
    in the process mesh, reductions are a jitted `psum` over the mesh
    (see mxnet_tpu.parallel for the fused-step path that makes this free).
  - 'dist_sync' / 'dist_async' / ...: multi-host via `jax.distributed`
    coordinator (the analog of the ps-lite scheduler rendezvous). Each host
    pushes into the global mesh; sync semantics come from the collective.

The server-side-optimizer trick (`set_optimizer` shipping an Updater to the
server, reference kvstore_dist_server.h:155) is preserved: the updater runs
wherever the store lives.
"""
from __future__ import annotations

import os
import pickle
from typing import Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray import NDArray
from .. import optimizer as opt_mod
from .. import telemetry as _telem


from .._dist_util import dist_client_active as _dist_client_active


class KVStore:
    """Base single-process store."""

    def __init__(self):
        self._store: Dict[Union[int, str], NDArray] = {}
        self._updater: Optional[Callable] = None
        self._opt_updater = None
        self._compression = {}
        self._comp_residual = {}

    def _supports_compression(self):
        return False

    # -- identity ----------------------------------------------------------
    @property
    def type(self):
        return "local"

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def get_rank(self):
        return self.rank

    def get_group_size(self):
        return self.num_workers

    # -- data --------------------------------------------------------------
    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                raise MXNetError(f"key {k} already initialized")
            self._store[k] = NDArray(v._data, v.ctx)

    def _normalize(self, key, value):
        if isinstance(key, (list, tuple)):
            out_v = []
            for v in value:
                out_v.append(v)
            return list(key), out_v
        return [key], [value]

    def _reduce(self, vals: List[NDArray]) -> NDArray:
        if len(vals) == 1:
            return vals[0]
        acc = vals[0]._data
        for v in vals[1:]:
            acc = acc + v._data
        # preserve stype: summed row_sparse grads stay row_sparse so
        # lazy_update optimizers keep their dispatch
        return type(vals[0])(acc, vals[0].ctx)

    def _cross(self, merged: NDArray) -> NDArray:
        """Cross-worker aggregation hook; identity for single-process
        stores, allgather-sum in KVStoreDist."""
        return merged

    # telemetry (mx.telemetry): each public comm entry point is decorated
    # with bytes-moved/timing accounting + an xplane TraceAnnotation; the
    # scopes are re-entrant so pushpull -> push/pull counts once. Disabled
    # cost: one wrapper call + module-flag check per call.
    @_telem.instrument_comm("push")
    def push(self, key, value, priority=0):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            vlist = v if isinstance(v, (list, tuple)) else [v]
            # order matters: local device reduce -> 2-bit quantize -> cross-
            # worker sum, so the compressed tensor is what rides the wire
            merged = self._cross(self._compress(k, self._reduce(vlist)))
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            if self._updater is not None:
                self._updater(k, merged, self._store[k])
            else:
                # reference default: the aggregated push value REPLACES the
                # stored value (kv.push(3, ones*8); kv.pull(3) -> 8)
                self._store[k]._set_data(
                    merged._data.astype(self._store[k].dtype))

    @_telem.instrument_comm("pull")
    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = self._normalize(key, out)
        for k, o in zip(keys, outs):
            olist = o if isinstance(o, (list, tuple)) else [o]
            src = self._store[k]
            for t in olist:
                t._set_data(src._data.astype(t.dtype))

    @_telem.instrument_comm("pushpull")
    def pushpull(self, key, value, out=None, priority=0):
        """Fused allreduce-style op (reference MXKVStorePushPullEx).

        A LIST of keys with no store-side updater rides the bucketed path:
        the merged values are flattened into dtype-homogeneous fusion
        buckets (parallel/zero.py planner, MXNET_TPU_BUCKET_BYTES) and
        cross-reduced with one collective per bucket instead of one per
        key — the same bucketed reduce-scatter the ZeRO-style fused step
        uses, so gluon Trainer's batched allreduce_grads benefits too."""
        if (self._updater is None and not self._compression
                and isinstance(key, (list, tuple)) and len(key) > 1
                and self._pushpull_bucketed(key, value, out)):
            return
        keys, values = self._normalize(key, value)
        for idx, (k, v) in enumerate(zip(keys, values)):
            vlist = v if isinstance(v, (list, tuple)) else [v]
            merged = self._cross(self._compress(k, self._reduce(vlist)))
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError(f"key {k} not initialized")
                self._updater(k, merged, self._store[k])
                src = self._store[k]
            else:
                # push-then-pull: persist the merged value like push does
                if k in self._store:
                    self._store[k]._set_data(
                        merged._data.astype(self._store[k].dtype))
                src = merged
            if out is not None:
                o = out[idx] if isinstance(out, (list, tuple)) and isinstance(key, (list, tuple)) else out
                olist = o if isinstance(o, (list, tuple)) else [o]
                for t in olist:
                    t._set_data(src._data.astype(t.dtype))

    def _pushpull_bucketed(self, keys, values, out=None):
        """Bucketed pushpull body: returns False when any key is unsuitable
        (row_sparse / non-float values) so the caller falls back to the
        per-key path. The local device reduce runs per BUCKET when every
        key carries the same contributor count (the Trainer case: one grad
        per device for every param) — the contributors' flat buckets stack
        into one fused fp32 reduction (``zero._k_bucket_reduce``) instead
        of one reduction per key; then one cross reduction per bucket
        (``_cross_bucket``), then the per-key store/out write-back with
        the same semantics as the per-key loop."""
        keys, vals = self._normalize(keys, values)
        vlists = []
        for v in vals:
            vlist = list(v) if isinstance(v, (list, tuple)) else [v]
            if any(getattr(x, "stype", "default") != "default" or
                   not jnp.issubdtype(x._data.dtype, jnp.floating)
                   for x in vlist):
                return False
            vlists.append(vlist)
        from ..parallel import zero as _zero
        from ..base import env as _env
        buckets = _zero.plan_buckets(
            [(i, v[0]._data.shape, v[0]._data.dtype)
             for i, v in enumerate(vlists)],
            ndp=1, bucket_bytes=int(_env.get("MXNET_TPU_BUCKET_BYTES")))
        dtypes = [v[0]._data.dtype for v in vlists]
        counts = {len(v) for v in vlists}
        reduced = [None] * len(keys)
        if counts == {1}:
            raws = [v[0]._data for v in vlists]
            for b in buckets:
                flat = self._cross_bucket(_zero.flatten_bucket(b, raws))
                for i, arr in _zero.unflatten_bucket(b, flat):
                    reduced[i] = arr.astype(dtypes[i])
        elif len(counts) == 1:
            n = counts.pop()
            for b in buckets:
                stacked = jnp.stack(
                    [_zero.flatten_bucket(b, [v[c]._data for v in vlists])
                     for c in range(n)])
                flat = self._cross_bucket(_zero._k_bucket_reduce(stacked))
                for i, arr in _zero.unflatten_bucket(b, flat):
                    reduced[i] = arr.astype(dtypes[i])
        else:
            # ragged contributor counts: per-key local reduce, bucketed
            # cross reduction only
            raws = [self._reduce(v)._data for v in vlists]
            for b in buckets:
                flat = self._cross_bucket(_zero.flatten_bucket(b, raws))
                for i, arr in _zero.unflatten_bucket(b, flat):
                    reduced[i] = arr.astype(dtypes[i])
        for idx_k, (k, v0, r) in enumerate(zip(keys, vlists, reduced)):
            src = type(v0[0])(r, v0[0].ctx)
            if k in self._store:
                # push-then-pull: persist the merged value like push does
                self._store[k]._set_data(r.astype(self._store[k].dtype))
            if out is not None:
                o = out[idx_k] if isinstance(out, (list, tuple)) else out
                olist = o if isinstance(o, (list, tuple)) else [o]
                for t in olist:
                    t._set_data(src._data.astype(t.dtype))
        return True

    def _cross_bucket(self, flat):
        """Cross-worker reduction of one flat fusion bucket; identity for
        single-process stores (the per-key ``_reduce`` already summed the
        device list), one fused collective per bucket in KVStoreDist."""
        return flat

    @staticmethod
    def _fill_rows_out(t, rows, idx, table_shape):
        """Shared out-shape dispatch for row_sparse_pull: row_sparse form
        first — a full-shape out gets the rows scattered in place, others
        zero (takes precedence when the request size coincides with the
        table size); a rows-shaped out gets exactly the gathered rows."""
        if tuple(t.shape) == tuple(table_shape):
            full = jnp.zeros(table_shape, rows.dtype).at[idx].set(rows)
            t._set_data(full.astype(t.dtype))
        elif tuple(t.shape) == tuple(rows.shape):
            t._set_data(rows.astype(t.dtype))
        else:
            raise MXNetError(
                f"row_sparse_pull: out shape {t.shape} matches neither "
                f"the table {tuple(table_shape)} nor the gathered rows "
                f"{tuple(rows.shape)}")

    @_telem.instrument_comm("row_sparse_pull")
    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only given rows (reference kvstore.h:236). Dense-backed: the
        rows are gathered on device via XLA take."""
        keys, outs = self._normalize(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        for k, o, r in zip(keys, outs, rids):
            src = self._store[k]
            olist = o if isinstance(o, (list, tuple)) else [o]
            for t in olist:
                idx = r._data.astype(jnp.int32)
                rows = jnp.take(src._data, idx, axis=0)
                self._fill_rows_out(t, rows, idx, src.shape)

    @_telem.instrument_comm("broadcast")
    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    # -- optimizer ----------------------------------------------------------
    def set_optimizer(self, optimizer: "opt_mod.Optimizer"):
        self._opt_updater = opt_mod.get_updater(optimizer)
        self._updater = self._opt_updater

    def set_updater(self, updater: Callable):
        self._updater = updater

    @property
    def updater(self):
        return self._updater

    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression with error feedback (reference
        src/kvstore/gradient_compression.cc:60 SetTwoBitCompression).

        Each pushed gradient is quantized to {-threshold, 0, +threshold}
        (values >= threshold saturate, the rest round to zero) BEFORE the
        cross-device/worker sum; the quantization error is kept per key and
        added to the next push (error feedback), so the scheme is unbiased
        over time. On a TPU pod the 2-bit tensor is what rides the
        ICI/DCN collective — a 16x traffic cut, same as the reference's
        ps-lite path.

        As in the reference (kvstore_local.h SetGradientCompression raises
        for non-dist stores), compression is only supported on dist stores —
        a 'local'/'device' store silently quantizing gradients would degrade
        single-machine training with no signal."""
        if not self._supports_compression():
            raise MXNetError(
                "gradient compression is only supported on dist kvstore "
                f"types (got {type(self).__name__}); use kv.create('dist_sync') "
                "or DataParallelTrainer(..., compression=...) for the fused "
                "in-jit path")
        params = dict(compression_params)
        ctype = params.get("type", "2bit")
        if ctype not in ("2bit", "none"):
            raise MXNetError(f"unsupported gradient compression {ctype!r}")
        self._compression = params if ctype != "none" else {}
        self._comp_residual = {}

    def _compress(self, key, merged: NDArray) -> NDArray:
        if not self._compression:
            return merged
        thr = jnp.float32(self._compression.get("threshold", 0.5))
        res = self._comp_residual.get(key)
        g = merged._data + (res if res is not None else 0)
        q = jnp.where(g >= thr, thr,
                      jnp.where(g <= -thr, -thr, jnp.zeros_like(g)))
        self._comp_residual[key] = g - q
        return NDArray(q.astype(merged._data.dtype), merged.ctx)

    # -- sync / lifecycle ----------------------------------------------------
    def barrier(self):
        pass

    def wait(self, keys=None):
        for k, v in self._store.items():
            v.wait_to_read()

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._opt_updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._opt_updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._opt_updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._opt_updater.set_states(f.read())

    def get_num_dead_node(self, node_id=0):
        return 0

    def _barrier_before_exit(self):
        pass

    def __del__(self):
        pass


class KVStoreLocal(KVStore):
    @property
    def type(self):
        return "local"


class KVStoreDevice(KVStore):
    @property
    def type(self):
        return "device"


class KVStoreTPU(KVStore):
    """Mesh-aware store: values living on different mesh devices are reduced
    with a jitted psum (the reference's NCCL allreduce analog)."""

    @property
    def type(self):
        return "tpu"

    def _reduce(self, vals):
        if len(vals) == 1:
            return vals[0]
        # stack-and-sum compiles to one fused reduction
        acc = jnp.sum(jnp.stack([v._data for v in vals]), axis=0)
        return NDArray(acc, vals[0].ctx)


class KVStoreDist(KVStore):
    """Multi-host store over the jax.distributed coordinator.

    Sync mode matches the reference's dist_sync semantics (the ps-lite server
    summing each worker's pushed contribution, kvstore_dist_server.h:550):
    after the per-worker local device reduction, the merged value is summed
    ACROSS processes. Small tensors ride a host-mediated allgather; tensors
    of >= MXNET_KVSTORE_BIGARRAY_BOUND elements (reference kvstore_dist.h:606
    big-array sharding knob, default 1e6) go through a jitted XLA all-reduce
    over a one-device-per-process mesh — XLA lowers it to reduce-scatter +
    all-gather so the wire carries ~2x the tensor instead of the full tensor
    to every worker, the collective analog of the reference's key-sharded
    server transfer. The updater (server-side optimizer in the reference)
    then runs identically on every worker over the aggregated value, so
    replicas stay in lock-step without a server.

    Async mode is a REAL parameter server (kvstore/ps.py): every process
    runs a daemon server thread owning the keys that hash to its rank
    (EncodeDefaultKey analog); pushes are applied at the key's home on
    arrival — in arrival order, no barrier, exactly the reference
    dist_async contract (kvstore_dist_server.h:325) — and pulls fetch the
    home's current state, so worker A observes worker B's pushes without
    ever synchronizing. Single-host fallback behaves like 'local' with
    rank 0 of 1 (same as reference launched without a scheduler).

    PERFORMANCE NOTE: this class is the eager compatibility path. The fast
    multi-chip path is `parallel.DataParallelTrainer`, whose one-jit step
    lets XLA lower the gradient reduction to on-device psum; use this store
    for reference dist-script compatibility, not the inner training loop.
    """

    def _supports_compression(self):
        return True

    def __init__(self, sync=True):
        super().__init__()
        self._sync = sync
        self._rank = int(os.environ.get("MXNET_TPU_RANK",
                         os.environ.get("DMLC_WORKER_ID", "0")))
        self._size = int(os.environ.get("MXNET_TPU_NUM_WORKERS",
                         os.environ.get("DMLC_NUM_WORKER", "1")))
        coord = os.environ.get("MXNET_TPU_COORDINATOR",
                               os.environ.get("DMLC_PS_ROOT_URI"))
        if coord and self._size > 1 and not _dist_client_active():
            # NB: jax.process_count() would itself initialize the XLA
            # backend and forbid distributed.initialize — probe the
            # distributed client state instead (normally this already
            # happened at `import mxnet_tpu`, see _maybe_init_distributed)
            if ":" not in coord:
                coord = f"{coord}:{os.environ.get('DMLC_PS_ROOT_PORT', '9091')}"
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=self._size,
                                       process_id=self._rank)
        self._bigarray_bound = self._agree_bigarray_bound(int(os.environ.get(
            "MXNET_KVSTORE_BIGARRAY_BOUND", 1_000_000)))
        self._allreduce_cache = {}
        # real async parameter server: one daemon server thread per process
        # owning this rank's home keys; rendezvous via the coordinator KV
        self._ps_server = self._ps_client = None
        if not sync and jax.process_count() > 1:
            from . import ps as _ps
            self._ps_server = _ps.PSServer(lambda: self._updater)
            _ps.publish_address(self.rank, self._ps_server.port)
            self._ps_client = _ps.PSClient(_ps.resolve_address)

    @staticmethod
    def _agree_bigarray_bound(bound: int) -> int:
        """Every process must agree on the bound: it selects WHICH
        cross-process collective ``_cross`` runs (the proc-mesh XLA
        all-reduce above the bound, eager ``process_allgather`` below), so
        a per-host MXNET_KVSTORE_BIGARRAY_BOUND would send rank A into one
        rendezvous and rank B into the other — a silent fleet-wide hang,
        not a wrong answer (mxcheck collective-rank-conditional). Rank 0's
        value wins, matching the reference's server-side authority
        (kvstore_dist.h InitImpl). Construction is a uniform program point,
        so the broadcast itself is safe."""
        if jax.process_count() <= 1:
            return int(bound)
        import numpy as _np
        from jax.experimental import multihost_utils
        agreed = multihost_utils.broadcast_one_to_all(
            _np.asarray(bound, dtype=_np.int64))
        return int(agreed)

    def _home(self, key) -> int:
        """Key -> owning rank (reference kvstore_dist.h:606
        EncodeDefaultKey server assignment)."""
        import zlib
        return zlib.crc32(str(key).encode()) % self.num_workers

    @property
    def type(self):
        return "dist_sync" if self._sync else "dist_async"

    @property
    def rank(self):
        return self._rank if jax.process_count() == 1 else jax.process_index()

    @property
    def num_workers(self):
        return max(self._size, jax.process_count())

    def init(self, key, value):
        """Like the reference's server-side init: rank 0's initial value
        wins and is broadcast to every worker (kvstore_dist.h InitImpl —
        only rank 0's push initializes the server), so replicas start from
        identical parameters no matter how each process seeded its RNG."""
        super().init(key, value)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            keys, _ = self._normalize(key, value)
            for k in keys:
                stored = self._store[k]
                g = multihost_utils.process_allgather(stored._data)
                stored._set_data(g[0].astype(stored._data.dtype))
            if self._ps_client is not None:
                from .ps import _pack
                for k in keys:
                    if self.rank == 0:
                        resp = self._ps_client.request(
                            self._home(k),
                            ("init", k, _pack(self._store[k].asnumpy())))
                        if resp[0] != "ok":
                            raise MXNetError(
                                f"dist_async init of key {k} failed at its "
                                f"home server: {resp}")
                    # every rank blocks until the home server has the key,
                    # so a pull immediately after init can't race the seed
                    self._ps_client.wait_ready(self._home(k), k)

    # -- async (parameter-server) paths -------------------------------------
    @_telem.instrument_comm("push")
    def push(self, key, value, priority=0):
        if self._ps_client is None:
            return super().push(key, value, priority)
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            vlist = v if isinstance(v, (list, tuple)) else [v]
            merged = self._compress(k, self._reduce(vlist))
            # the HOME server applies its updater on arrival (server-side
            # optimizer, kvstore_dist_server.h:155); no local update here.
            # stype rides along so a row_sparse push keeps lazy semantics
            # at the server.
            from .ps import _pack
            # ps protocol boundary: the payload is serialized over a
            # socket, so the host copy is the transport, not a stray sync
            resp = self._ps_client.request(
                self._home(k), ("push", k, _pack(merged.asnumpy()),  # mxlint: disable=host-sync
                                getattr(merged, "stype", "default")))
            if resp[0] != "ok":
                raise MXNetError(
                    f"dist_async push of key {k} failed: {resp}")

    @_telem.instrument_comm("pull")
    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if self._ps_client is None:
            return super().pull(key, out, priority, ignore_sparse)
        keys, outs = self._normalize(key, out)
        for k, o in zip(keys, outs):
            cur = self._ps_client.pull_blocking(self._home(k), k)
            olist = o if isinstance(o, (list, tuple)) else [o]
            for t in olist:
                t._set_data(jnp.asarray(cur).astype(t.dtype))

    @_telem.instrument_comm("pushpull")
    def pushpull(self, key, value, out=None, priority=0):
        if self._ps_client is None:
            return super().pushpull(key, value, out, priority)
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    @_telem.instrument_comm("row_sparse_pull")
    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        if self._ps_client is None:
            return super().row_sparse_pull(key, out, priority, row_ids)
        import numpy as _np
        keys, outs = self._normalize(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        for k, o, r in zip(keys, outs, rids):
            # ps protocol boundary: row ids ship host-side to the server
            ids = _np.asarray(r.asnumpy(), dtype=_np.int64)  # mxlint: disable=host-sync
            resp = self._ps_client.request(self._home(k),
                                           ("pull_rows", k, ids))
            if resp[0] != "ok":
                # "missing" means uninitialized; "error" carries the real
                # server-side failure (e.g. out-of-range row ids)
                raise MXNetError(
                    f"row_sparse_pull of key {k} failed: "
                    + ("not initialized at its home server"
                       if resp[0] == "missing" else repr(resp)))
            from .ps import _unpack
            rows = jnp.asarray(_unpack(resp[1]))
            olist = o if isinstance(o, (list, tuple)) else [o]
            for t in olist:
                self._fill_rows_out(t, rows, jnp.asarray(ids),
                                    self._store[k].shape)

    # -- server-side optimizer installation ---------------------------------
    def set_optimizer(self, optimizer: "opt_mod.Optimizer"):
        super().set_optimizer(optimizer)
        self._updater_installed_barrier()

    def set_updater(self, updater):
        super().set_updater(updater)
        self._updater_installed_barrier()

    def _updater_installed_barrier(self):
        """dist_async: no rank may push before EVERY home server has its
        updater installed. Without this, rank 0 can init a key and push
        while the home process has not yet executed set_optimizer — the
        push is then applied with assignment semantics instead of the
        server-side optimizer, silently corrupting server state. The
        reference ships the optimizer to every server before training
        (kvstore_dist_server.h:155 CommandHandle/set optimizer); here the
        installation is local to each process's server thread, so a
        cross-process barrier after it gives the same ordering guarantee:
        any rank that returns from set_optimizer/set_updater (and can
        therefore push) knows every home already has its updater.

        CONTRACT: under dist_async EVERY rank must call
        set_optimizer/set_updater (the symmetric pattern Module/Trainer
        use) — each process hosts a server thread, so each needs its own
        updater anyway. The handshake rides the coordinator KV with a
        TIMEOUT, so an asymmetric call fails loudly after 120s naming the
        missing rank instead of deadlocking a collective forever."""
        if self._ps_client is None or jax.process_count() <= 1:
            return
        from .ps import coordinator_kv
        client = coordinator_kv()
        if client is None:
            return
        # gen advances ONLY on success, and publication is idempotent per
        # gen — so a rank that caught a timeout and retries re-runs the SAME
        # generation instead of desyncing one ahead of everyone forever
        gen = getattr(self, "_updater_gen", 0) + 1
        published = getattr(self, "_updater_pub", None)
        if published is None:
            published = self._updater_pub = set()
        if gen not in published:
            client.key_value_set(f"mxtpu_ps_updater/{gen}/{self.rank}", "1")
            published.add(gen)
        for r in range(self.num_workers):
            try:
                client.blocking_key_value_get(
                    f"mxtpu_ps_updater/{gen}/{r}", 120_000)
            except Exception as e:
                raise MXNetError(
                    f"dist_async set_optimizer/set_updater must run on "
                    f"EVERY rank (each process hosts a server needing its "
                    f"updater); rank {r} did not install call #{gen} "
                    f"within 120s") from e
        self._updater_gen = gen

    # -- sync collective path ------------------------------------------------
    def _proc_mesh(self):
        """One device per process, axis 'proc' — the DCN-spanning mesh the
        big-tensor all-reduce runs over."""
        from jax.sharding import Mesh
        import numpy as _np
        seen, picked = set(), []
        for d in sorted(jax.devices(), key=lambda d: (d.process_index, d.id)):
            if d.process_index not in seen:
                seen.add(d.process_index)
                picked.append(d)
        return Mesh(_np.array(picked), ("proc",))

    def _allreduce_xla(self, x):
        """Cross-process sum via ONE jitted XLA all-reduce (lowered to
        reduce-scatter + all-gather on the wire): ~2x tensor bytes per
        worker instead of the N x full-tensor allgather — the collective
        analog of the reference's key-sharded server transfer
        (kvstore_dist.h:606 EncodeDefaultKey + BIGARRAY_BOUND).
        Accumulates in float32 (and returns float32) so a bf16-compressed
        wire dtype never degrades the sum; callers cast back."""
        import numpy as _np
        from jax.sharding import NamedSharding, PartitionSpec as P
        key = (tuple(x.shape), str(x.dtype))
        cached = self._allreduce_cache.get(key)
        if cached is None:
            mesh = self._proc_mesh()
            sh_in = NamedSharding(mesh, P("proc"))
            sh_out = NamedSharding(mesh, P())
            fn = jax.jit(lambda a: jnp.sum(a.astype(jnp.float32), axis=0),
                         out_shardings=sh_out)
            cached = (fn, sh_in)
            self._allreduce_cache[key] = cached
        fn, sh_in = cached
        xg = jax.make_array_from_process_local_data(
            sh_in, _np.asarray(x)[None])
        out = fn(xg)
        return jnp.asarray(out.addressable_data(0))

    def _cross_bucket(self, flat):
        """One fused cross-process reduction per fusion bucket. The wire
        dtype honors MXNET_TPU_COMM_DTYPE='bfloat16' (half the DCN bytes;
        accumulation stays fp32 inside _allreduce_xla). int8 is only
        offered by the fused zero step, whose chunk scales live inside the
        same jit — an eager per-bucket requantization here would cost more
        than it saves."""
        if not (self._sync and jax.process_count() > 1):
            return flat
        from ..parallel import zero as _zero
        comm = _zero.canonical_comm_dtype(
            os.environ.get("MXNET_TPU_COMM_DTYPE") or None)
        if comm == "bfloat16":
            flat = flat.astype(jnp.bfloat16)
        return self._allreduce_xla(flat)

    def _cross(self, merged):
        if self._sync and jax.process_count() > 1:
            x = merged._data
            cls = type(merged)  # keep row_sparse stype through the sum
            if x.size >= self._bigarray_bound:
                return cls(self._allreduce_xla(x).astype(x.dtype),
                           merged.ctx)
            from jax.experimental import multihost_utils
            g = multihost_utils.process_allgather(x)
            summed = jnp.sum(g, axis=0).astype(x.dtype)
            return cls(summed, merged.ctx)
        return merged

    def barrier(self):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("kvstore_barrier")

    def _barrier_before_exit(self):
        self.barrier()


_KVSTORE_TYPES = {
    "local": KVStoreLocal,
    "local_allreduce_cpu": KVStoreLocal,
    "local_allreduce_device": KVStoreDevice,
    "device": KVStoreDevice,
    "nccl": KVStoreTPU,      # alias: reference NCCL == TPU collectives
    "tpu": KVStoreTPU,
    "dist": KVStoreDist,
    "dist_sync": KVStoreDist,
    "dist_device_sync": KVStoreDist,
    "dist_sync_device": KVStoreDist,
}


def create(name="local") -> KVStore:
    """reference src/kvstore/kvstore.cc:40 factory."""
    if not isinstance(name, str):
        raise MXNetError("kvstore name must be a string")
    key = name.lower()
    if key in ("dist_async", "dist_async_device", "dist_device_async"):
        return KVStoreDist(sync=False)
    if key in _KVSTORE_TYPES:
        cls = _KVSTORE_TYPES[key]
        if cls is KVStoreDist:
            return KVStoreDist(sync=True)
        return cls()
    raise MXNetError(f"unknown kvstore type {name!r}")
