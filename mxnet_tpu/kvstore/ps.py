"""Threaded TCP parameter server backing `dist_async` (reference
src/kvstore/kvstore_dist_server.h:325 KVStoreDistServer::DataHandleDefault,
ps-lite push/pull RPC).

The reference runs dedicated server processes; each key lives on the server
chosen by `EncodeDefaultKey` (kvstore_dist.h:606) and every worker push is
applied to that server's state ON ARRIVAL — async workers observe each
other's updates through the server without any barrier. TPU-native we fold
the server role into the workers: every process runs one daemon server
thread owning the keys that hash to its rank, and the jax.distributed
coordinator's key-value store provides the address rendezvous (the ps-lite
scheduler analog). The *sync* path never touches this module — lock-step
aggregation rides XLA collectives (see KVStoreDist._cross).

Wire format: length-prefixed pickles of (op, ...) tuples carrying numpy
payloads. This is a compatibility/control path, not the tensor fast path —
bulk training traffic belongs in the fused one-jit trainer whose gradient
reduction lowers to ICI/DCN collectives.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

from ..base import MXNetError

_HDR = struct.Struct("<Q")


def _send_msg(sock: socket.socket, obj) -> None:
    data = pickle.dumps(obj, protocol=4)
    sock.sendall(_HDR.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        c = sock.recv(min(n - got, 1 << 20))
        if not c:
            raise ConnectionError("peer closed")
        chunks.append(c)
        got += len(c)
    return b"".join(chunks)


def _recv_msg(sock: socket.socket):
    (n,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    return pickle.loads(_recv_exact(sock, n))


def _pack(arr) -> tuple:
    a = np.asarray(arr)
    return (str(a.dtype), a.shape, a.tobytes())


def _unpack(payload) -> np.ndarray:
    dtype, shape, raw = payload
    return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()


class PSServer:
    """One daemon thread per process serving this rank's home keys.

    Requests (all answered synchronously on the caller's connection):
      ("init", key, payload)     -> ("ok",)      first init wins
      ("push", key, payload[, stype]) -> ("ok",) apply updater / assign
      ("pull", key)              -> ("ok", payload) | ("missing",)
      ("pull_rows", key, ids)    -> ("ok", payload)  gathered rows only
      ("has", key)               -> ("ok",) | ("missing",)

    Locking is PER KEY (plus a registry guard): arrival order is preserved
    for each key — the reference server's per-key consistency contract —
    while pushes/pulls of different keys proceed concurrently even when an
    updater call compiles.
    """

    def __init__(self, get_updater: Callable[[], Optional[Callable]]):
        self._get_updater = get_updater
        self._store: Dict = {}
        self._guard = threading.Lock()
        self._key_locks: Dict = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("", 0))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="mxtpu-ps-server")
        self._thread.start()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            while True:
                msg = _recv_msg(conn)
                _send_msg(conn, self._handle(msg))
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def _key_lock(self, key) -> threading.Lock:
        with self._guard:
            lock = self._key_locks.get(key)
            if lock is None:
                lock = self._key_locks[key] = threading.Lock()
            return lock

    def _handle(self, msg):
        op, key = msg[0], msg[1]
        if op == "init":
            with self._key_lock(key):
                # first init wins (rank 0 is the only sender — reference
                # InitImpl: only rank 0's push initializes the server)
                if key not in self._store:
                    self._store[key] = _unpack(msg[2])
            return ("ok",)
        if op == "push":
            grad = _unpack(msg[2])
            stype = msg[3] if len(msg) > 3 else "default"
            with self._key_lock(key):
                if key not in self._store:
                    return ("missing",)
                stored = self._store[key]
                updater = self._get_updater()
                if updater is None:
                    # reference default: pushed value replaces server state
                    self._store[key] = grad.astype(stored.dtype)
                else:
                    # server-side optimizer: the updater mutates the stored
                    # NDArray in place (kvstore_dist_server.h:155); a
                    # row_sparse push keeps its stype so lazy_update
                    # optimizers apply reference lazy semantics
                    from ..ndarray import NDArray
                    import jax.numpy as jnp
                    g_nd = NDArray(jnp.asarray(grad))
                    if stype == "row_sparse":
                        from ..ndarray.sparse import RowSparseNDArray
                        g_nd = RowSparseNDArray(g_nd._data, g_nd.ctx)
                    s_nd = NDArray(jnp.asarray(stored))
                    updater(key, g_nd, s_nd)
                    self._store[key] = np.asarray(s_nd._data)
            return ("ok",)
        if op == "pull":
            with self._key_lock(key):
                if key not in self._store:
                    return ("missing",)
                return ("ok", _pack(self._store[key]))
        if op == "pull_rows":
            ids = np.asarray(msg[2], dtype=np.int64)
            with self._key_lock(key):
                if key not in self._store:
                    return ("missing",)
                return ("ok", _pack(self._store[key][ids]))
        if op == "has":
            with self._key_lock(key):
                return ("ok",) if key in self._store else ("missing",)
        return ("error", f"unknown op {op!r}")

    def close(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class PSClient:
    """Per-process client: one persistent connection per home rank."""

    def __init__(self, addr_of: Callable[[int], str]):
        self._addr_of = addr_of
        self._conns: Dict[int, socket.socket] = {}
        self._locks: Dict[int, threading.Lock] = {}
        self._guard = threading.Lock()

    def _conn(self, home: int):
        with self._guard:
            lock = self._locks.setdefault(home, threading.Lock())
        return lock

    def request(self, home: int, msg, retries: int = 1):
        lock = self._conn(home)
        with lock:
            for attempt in range(retries + 1):
                sock = self._conns.get(home)
                try:
                    if sock is None:
                        host, port = self._addr_of(home).rsplit(":", 1)
                        sock = socket.create_connection((host, int(port)),
                                                        timeout=120)
                        sock.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_NODELAY, 1)
                        self._conns[home] = sock
                    _send_msg(sock, msg)
                    return _recv_msg(sock)
                except (ConnectionError, OSError):
                    self._conns.pop(home, None)
                    if attempt == retries:
                        raise
        raise MXNetError("unreachable")

    def _wait_until(self, home: int, key, msg, timeout: float):
        deadline = time.monotonic() + timeout
        while True:
            resp = self.request(home, msg)
            if resp[0] == "ok":
                return resp
            if time.monotonic() > deadline:
                raise MXNetError(
                    f"dist_async: key {key!r} never initialized at its home "
                    f"server (rank {home}) within {timeout}s")
            time.sleep(0.02)

    def pull_blocking(self, home: int, key, timeout: float = 120.0):
        """Pull that waits for the key to be initialized at its home —
        covers the init race where rank 0's init is still in flight."""
        return _unpack(self._wait_until(home, key, ("pull", key), timeout)[1])

    def wait_ready(self, home: int, key, timeout: float = 120.0):
        """Readiness probe without the tensor payload (a few bytes on the
        wire, not the full table) — used by init on every rank."""
        self._wait_until(home, key, ("has", key), timeout)

    def close(self):
        for s in self._conns.values():
            try:
                s.close()
            except OSError:
                pass
        self._conns.clear()


def coordinator_kv():
    """The jax.distributed coordinator's key-value store — the rendezvous
    channel every process can reach (the ps-lite scheduler analog). Returns
    None when no distributed client is active."""
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:
        return None


def publish_address(rank: int, port: int) -> None:
    client = coordinator_kv()
    if client is None:
        raise MXNetError(
            "dist_async needs the jax.distributed coordinator for address "
            "rendezvous; launch through tools/launch.py or set "
            "MXNET_TPU_COORDINATOR")
    import os
    host = os.environ.get("MXNET_TPU_PS_HOST") or socket.gethostname()
    client.key_value_set(f"mxtpu_ps/{rank}", f"{host}:{port}")


def resolve_address(rank: int, timeout_ms: int = 120_000) -> str:
    client = coordinator_kv()
    if client is None:
        raise MXNetError("no jax.distributed coordinator client")
    return client.blocking_key_value_get(f"mxtpu_ps/{rank}", timeout_ms)
