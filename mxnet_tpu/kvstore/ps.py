"""Threaded TCP parameter server backing `dist_async` (reference
src/kvstore/kvstore_dist_server.h:325 KVStoreDistServer::DataHandleDefault,
ps-lite push/pull RPC).

The reference runs dedicated server processes; each key lives on the server
chosen by `EncodeDefaultKey` (kvstore_dist.h:606) and every worker push is
applied to that server's state ON ARRIVAL — async workers observe each
other's updates through the server without any barrier. TPU-native we fold
the server role into the workers: every process runs one daemon server
thread owning the keys that hash to its rank, and the jax.distributed
coordinator's key-value store provides the address rendezvous (the ps-lite
scheduler analog). The *sync* path never touches this module — lock-step
aggregation rides XLA collectives (see KVStoreDist._cross).

Wire format: length-prefixed pickles of (op, ...) tuples carrying numpy
payloads. This is a compatibility/control path, not the tensor fast path —
bulk training traffic belongs in the fused one-jit trainer whose gradient
reduction lowers to ICI/DCN collectives.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

from ..base import MXNetError

_HDR = struct.Struct("<Q")

# a duplicate's server-side wait for the in-flight original MUST stay under
# the client's recv timeout, or the waiter's reply can never reach a live
# client and a fresh-seq re-push double-applies
_CLIENT_RECV_TIMEOUT = 600.0
_INFLIGHT_WAIT = _CLIENT_RECV_TIMEOUT - 10.0


def _send_msg(sock: socket.socket, obj) -> None:
    data = pickle.dumps(obj, protocol=4)
    sock.sendall(_HDR.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        c = sock.recv(min(n - got, 1 << 20))
        if not c:
            raise ConnectionError("peer closed")
        chunks.append(c)
        got += len(c)
    return b"".join(chunks)


def _recv_msg(sock: socket.socket):
    (n,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    return pickle.loads(_recv_exact(sock, n))


def _pack(arr) -> tuple:
    a = np.asarray(arr)
    return (str(a.dtype), a.shape, a.tobytes())


def _unpack(payload) -> np.ndarray:
    dtype, shape, raw = payload
    return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()


class PSServer:
    """One daemon thread per process serving this rank's home keys.

    Requests (all answered synchronously on the caller's connection):
      ("init", key, payload)     -> ("ok",)      first init wins
      ("push", key, payload[, stype]) -> ("ok",) apply updater / assign
      ("pull", key)              -> ("ok", payload) | ("missing",)
      ("pull_rows", key, ids)    -> ("ok", payload)  gathered rows only
      ("has", key)               -> ("ok",) | ("missing",)

    Requests may arrive wrapped in an exactly-once envelope
    ("req", client_id, seq, inner): the server remembers the last (seq,
    response) per client and REPLAYS the response for a duplicate seq
    instead of re-applying it — so a client retry after a lost reply cannot
    apply the same gradient twice (the ps-lite message-seq dedupe,
    reference ps-lite van.cc resender).

    Locking is PER KEY (plus a registry guard): arrival order is preserved
    for each key — the reference server's per-key consistency contract —
    while pushes/pulls of different keys proceed concurrently even when an
    updater call compiles.
    """

    def __init__(self, get_updater: Callable[[], Optional[Callable]]):
        self._get_updater = get_updater
        self._store: Dict = {}
        self._guard = threading.Lock()
        self._key_locks: Dict = {}
        # exactly-once dedupe: client_id -> (last seq, cached response)
        self._dedup: Dict[str, tuple] = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("", 0))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="mxtpu-ps-server")
        self._thread.start()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            while True:
                msg = _recv_msg(conn)
                try:
                    # _handle does NO socket I/O, so ANY exception here is a
                    # handler failure (including OSError from a user updater
                    # touching the filesystem) and must reach the CLIENT as
                    # an error reply — never kill the connection replyless
                    resp = self._handle(msg)
                except Exception as e:  # noqa: BLE001 - surface to client
                    resp = ("error", f"{type(e).__name__}: {e}"[:500])
                _send_msg(conn, resp)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def _key_lock(self, key) -> threading.Lock:
        with self._guard:
            lock = self._key_locks.get(key)
            if lock is None:
                lock = self._key_locks[key] = threading.Lock()
            return lock

    def _handle(self, msg):
        if msg[0] == "req":
            # exactly-once envelope: dedupe MUTATING ops by (client, seq) —
            # a retry whose original was applied (or is STILL APPLYING) gets
            # the original's reply, never a second application. The in-flight
            # marker (an Event) closes the check-then-act window where a
            # retry races a slow original: the retry waits for the original
            # to finish instead of re-running the updater. Idempotent ops
            # (pull/has/pull_rows) just re-execute.
            _, cid, seq, inner = msg
            if inner[0] in ("push", "init"):
                with self._guard:
                    last = self._dedup.get(cid)
                    if last is not None and last[0] == seq:
                        pending = last[1]
                    elif last is not None and last[0] > seq:
                        # a duplicate older than the newest cached entry is
                        # unreachable through PSClient (the per-home lock
                        # serializes retries before any newer send); never
                        # fabricate success for an unknown outcome
                        return ("error", "superseded duplicate seq")
                    else:
                        pending = None
                        self._dedup[cid] = (seq, threading.Event())
                if pending is not None:
                    if isinstance(pending, threading.Event):
                        # just under the client's recv timeout, so the
                        # waiter's reply still reaches a live client; an
                        # updater slower than client patience (two full
                        # attempts) is out of contract and surfaces as an
                        # error below rather than hanging forever
                        pending.wait(timeout=_INFLIGHT_WAIT)
                        with self._guard:
                            last = self._dedup.get(cid)
                            if last is not None and last[0] == seq and \
                                    not isinstance(last[1], threading.Event):
                                return last[1]
                        # never fabricate success: the original did not
                        # complete, so the client must see a failure
                        return ("error", "in-flight duplicate never completed")
                    return pending
                resp = err = None
                try:
                    resp = self._handle(inner)
                except Exception as e:  # noqa: BLE001 - cache then re-raise
                    err = f"{type(e).__name__}: {e}"[:500]
                    raise
                finally:
                    # ALWAYS release waiters — an updater exception must not
                    # leave the Event unset (a retry would block the full
                    # in-flight wait and report a lost gradient as applied). Cache the REAL
                    # error text so a retry replays the diagnosable message.
                    # Replace only our own entry: a slow original must not
                    # clobber a newer request's cache with its older seq.
                    with self._guard:
                        cur = self._dedup.get(cid)
                        if cur is not None and cur[0] == seq:
                            final = resp if resp is not None else \
                                ("error", err or "apply raised at the server")
                            self._dedup[cid] = (seq, final)
                            if isinstance(cur[1], threading.Event):
                                cur[1].set()
                return resp
            return self._handle(inner)
        op, key = msg[0], msg[1]
        if op == "init":
            with self._key_lock(key):
                # first init wins (rank 0 is the only sender — reference
                # InitImpl: only rank 0's push initializes the server)
                if key not in self._store:
                    self._store[key] = _unpack(msg[2])
            return ("ok",)
        if op == "push":
            grad = _unpack(msg[2])
            stype = msg[3] if len(msg) > 3 else "default"
            with self._key_lock(key):
                if key not in self._store:
                    return ("missing",)
                stored = self._store[key]
                updater = self._get_updater()
                if updater is None:
                    # reference default: pushed value replaces server state
                    self._store[key] = grad.astype(stored.dtype)
                else:
                    # server-side optimizer: the updater mutates the stored
                    # NDArray in place (kvstore_dist_server.h:155); a
                    # row_sparse push keeps its stype so lazy_update
                    # optimizers apply reference lazy semantics
                    from ..ndarray import NDArray
                    import jax.numpy as jnp
                    g_nd = NDArray(jnp.asarray(grad))
                    if stype == "row_sparse":
                        from ..ndarray.sparse import RowSparseNDArray
                        g_nd = RowSparseNDArray(g_nd._data, g_nd.ctx)
                    s_nd = NDArray(jnp.asarray(stored))
                    updater(key, g_nd, s_nd)
                    self._store[key] = np.asarray(s_nd._data)
            return ("ok",)
        if op == "pull":
            with self._key_lock(key):
                if key not in self._store:
                    return ("missing",)
                return ("ok", _pack(self._store[key]))
        if op == "pull_rows":
            ids = np.asarray(msg[2], dtype=np.int64)
            with self._key_lock(key):
                if key not in self._store:
                    return ("missing",)
                return ("ok", _pack(self._store[key][ids]))
        if op == "has":
            with self._key_lock(key):
                return ("ok",) if key in self._store else ("missing",)
        return ("error", f"unknown op {op!r}")

    def close(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class PSClient:
    """Per-process client: one persistent connection per home rank."""

    def __init__(self, addr_of: Callable[[int], str]):
        import uuid
        self._addr_of = addr_of
        self._conns: Dict[int, socket.socket] = {}
        self._locks: Dict[int, threading.Lock] = {}
        self._guard = threading.Lock()
        self._id = uuid.uuid4().hex
        self._seq = 0

    def _conn(self, home: int):
        with self._guard:
            lock = self._locks.setdefault(home, threading.Lock())
        return lock

    def request(self, home: int, msg, retries: int = 1):
        lock = self._conn(home)
        with lock:
            # one seq per LOGICAL request (assigned before the retry loop):
            # a resend after a dropped connection carries the same seq, so
            # the server replays instead of re-applying a mutating op
            with self._guard:
                self._seq += 1
                seq = self._seq
            wire = ("req", self._id, seq, msg)
            for attempt in range(retries + 1):
                sock = self._conns.get(home)
                try:
                    if sock is None:
                        host, port = self._addr_of(home).rsplit(":", 1)
                        sock = socket.create_connection((host, int(port)),
                                                        timeout=120)
                        # recv timeout must EXCEED the server's in-flight
                        # duplicate wait (see _INFLIGHT_WAIT), or a slow but
                        # successful push times out client-side and a fresh
                        # seq re-push double-applies — the exact failure
                        # dedupe prevents
                        sock.settimeout(_CLIENT_RECV_TIMEOUT)
                        sock.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_NODELAY, 1)
                        self._conns[home] = sock
                    _send_msg(sock, wire)
                    return _recv_msg(sock)
                except (ConnectionError, OSError):
                    self._conns.pop(home, None)
                    if attempt == retries:
                        raise
        raise MXNetError("unreachable")

    def _wait_until(self, home: int, key, msg, timeout: float):
        deadline = time.monotonic() + timeout
        while True:
            resp = self.request(home, msg)
            if resp[0] == "ok":
                return resp
            if resp[0] == "error":
                # a server-side failure is terminal — don't spin on it for
                # the whole timeout and then misreport 'never initialized'
                raise MXNetError(
                    f"dist_async: server error for key {key!r} at rank "
                    f"{home}: {resp[1] if len(resp) > 1 else resp}")
            if time.monotonic() > deadline:
                raise MXNetError(
                    f"dist_async: key {key!r} never initialized at its home "
                    f"server (rank {home}) within {timeout}s")
            time.sleep(0.02)

    def pull_blocking(self, home: int, key, timeout: float = 120.0):
        """Pull that waits for the key to be initialized at its home —
        covers the init race where rank 0's init is still in flight."""
        return _unpack(self._wait_until(home, key, ("pull", key), timeout)[1])

    def wait_ready(self, home: int, key, timeout: float = 120.0):
        """Readiness probe without the tensor payload (a few bytes on the
        wire, not the full table) — used by init on every rank."""
        self._wait_until(home, key, ("has", key), timeout)

    def close(self):
        for s in self._conns.values():
            try:
                s.close()
            except OSError:
                pass
        self._conns.clear()


def coordinator_kv():
    """The jax.distributed coordinator's key-value store — the rendezvous
    channel every process can reach (the ps-lite scheduler analog). Returns
    None when no distributed client is active."""
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:
        return None


def publish_address(rank: int, port: int) -> None:
    client = coordinator_kv()
    if client is None:
        raise MXNetError(
            "dist_async needs the jax.distributed coordinator for address "
            "rendezvous; launch through tools/launch.py or set "
            "MXNET_TPU_COORDINATOR")
    import os
    host = os.environ.get("MXNET_TPU_PS_HOST") or socket.gethostname()
    client.key_value_set(f"mxtpu_ps/{rank}", f"{host}:{port}")


def resolve_address(rank: int, timeout_ms: int = 120_000) -> str:
    client = coordinator_kv()
    if client is None:
        raise MXNetError("no jax.distributed coordinator client")
    return client.blocking_key_value_get(f"mxtpu_ps/{rank}", timeout_ms)
