from .kvstore import KVStore, KVStoreLocal, KVStoreTPU, create

__all__ = ["KVStore", "KVStoreLocal", "KVStoreTPU", "create"]
