// Native RecordIO runtime (TPU-framework analog of the reference's C++ IO
// stack: dmlc recordio + src/io/iter_image_recordio_2.cc threaded pipeline).
//
// Exposes a flat C ABI consumed via ctypes (mxnet_tpu/native/__init__.py):
//   - rio_index_build:    scan a .rec file -> (offset, length) table
//   - rio_reader_*:       background-thread prefetching record reader with a
//                         bounded ring buffer (the PrefetcherIter analog,
//                         reference src/io/iter_prefetcher.h:47)
//   - rio_writer_*:       buffered record writer
//
// Build: g++ -O2 -shared -fPIC -pthread recordio.cc -o libmxtpu_io.so
#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

struct Record {
  std::vector<char> data;
};

// ---------------------------------------------------------------------------
// Index scan
// ---------------------------------------------------------------------------

struct Index {
  std::vector<int64_t> offsets;
  std::vector<int64_t> lengths;
};

bool scan_file(const char* path, Index* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  uint32_t head[2];
  int64_t pos = 0;
  while (std::fread(head, sizeof(uint32_t), 2, f) == 2) {
    if (head[0] != kMagic) { std::fclose(f); return false; }
    int64_t len = head[1] & kLenMask;
    out->offsets.push_back(pos);
    out->lengths.push_back(len);
    int64_t padded = (len + 3) / 4 * 4;
    if (std::fseek(f, static_cast<long>(padded), SEEK_CUR) != 0) break;
    pos += 8 + padded;
  }
  std::fclose(f);
  return true;
}

// ---------------------------------------------------------------------------
// Threaded prefetch reader
// ---------------------------------------------------------------------------

struct Reader {
  std::string path;
  Index index;                 // optional (shuffle mode)
  bool use_index = false;
  uint64_t seed = 0;
  size_t capacity = 256;
  // ring
  std::deque<Record> ring;
  std::mutex mu;
  std::condition_variable cv_can_push, cv_can_pop;
  bool eof = false;
  bool stop = false;
  uint64_t epoch = 0;
  std::thread worker;

  void run() {
    FILE* f = std::fopen(path.c_str(), "rb");
    if (!f) { finish(); return; }
    std::vector<size_t> order;
    if (use_index) {
      order.resize(index.offsets.size());
      for (size_t i = 0; i < order.size(); ++i) order[i] = i;
      std::mt19937_64 rng(seed + epoch);
      std::shuffle(order.begin(), order.end(), rng);
    }
    size_t cursor = 0;
    while (true) {
      Record rec;
      if (use_index) {
        if (cursor >= order.size()) break;
        size_t i = order[cursor++];
        std::fseek(f, static_cast<long>(index.offsets[i]), SEEK_SET);
        uint32_t head[2];
        if (std::fread(head, sizeof(uint32_t), 2, f) != 2) break;
        int64_t len = head[1] & kLenMask;
        rec.data.resize(len);
        if (std::fread(rec.data.data(), 1, len, f) != static_cast<size_t>(len))
          break;
      } else {
        uint32_t head[2];
        if (std::fread(head, sizeof(uint32_t), 2, f) != 2) break;
        if (head[0] != kMagic) break;
        int64_t len = head[1] & kLenMask;
        rec.data.resize(len);
        if (std::fread(rec.data.data(), 1, len, f) != static_cast<size_t>(len))
          break;
        int64_t pad = (4 - len % 4) % 4;
        if (pad) std::fseek(f, static_cast<long>(pad), SEEK_CUR);
      }
      std::unique_lock<std::mutex> lk(mu);
      cv_can_push.wait(lk, [&] { return ring.size() < capacity || stop; });
      if (stop) break;
      ring.push_back(std::move(rec));
      cv_can_pop.notify_one();
    }
    std::fclose(f);
    finish();
  }

  void finish() {
    std::lock_guard<std::mutex> lk(mu);
    eof = true;
    cv_can_pop.notify_all();
  }
};

struct Writer {
  FILE* f = nullptr;
};

}  // namespace

extern "C" {

// --- index -----------------------------------------------------------------

// Returns number of records, or -1 on error. Call with nullptrs to get the
// count, then with arrays of capacity `cap`; the copy is bounded by cap so a
// file that grew between the two calls cannot overflow the caller's buffers.
int64_t rio_index_build(const char* path, int64_t* offsets, int64_t* lengths,
                        int64_t cap) {
  Index idx;
  if (!scan_file(path, &idx)) return -1;
  int64_t n = static_cast<int64_t>(idx.offsets.size());
  if (offsets && lengths) {
    int64_t m = n < cap ? n : cap;
    std::memcpy(offsets, idx.offsets.data(), m * sizeof(int64_t));
    std::memcpy(lengths, idx.lengths.data(), m * sizeof(int64_t));
    return m;
  }
  return n;
}

// --- reader ----------------------------------------------------------------

void* rio_reader_create(const char* path, int64_t capacity, int shuffle,
                        uint64_t seed) {
  auto* r = new Reader();
  r->path = path;
  r->capacity = capacity > 0 ? static_cast<size_t>(capacity) : 256;
  // fail fast on a bad path: the worker opens the file again later, but a
  // create-time check lets the binding raise instead of yielding an
  // empty epoch
  FILE* probe = std::fopen(path, "rb");
  if (!probe) { delete r; return nullptr; }
  std::fclose(probe);
  if (shuffle) {
    if (!scan_file(path, &r->index)) { delete r; return nullptr; }
    r->use_index = true;
    r->seed = seed;
  }
  r->worker = std::thread([r] { r->run(); });
  return r;
}

// Copy next record into buf (size bufsize). Returns record length, -1 on
// end-of-epoch, or -2 if bufsize is too small (record stays queued).
int64_t rio_reader_next(void* handle, char* buf, int64_t bufsize) {
  auto* r = static_cast<Reader*>(handle);
  std::unique_lock<std::mutex> lk(r->mu);
  r->cv_can_pop.wait(lk, [&] { return !r->ring.empty() || r->eof; });
  if (r->ring.empty()) return -1;
  Record& rec = r->ring.front();
  int64_t len = static_cast<int64_t>(rec.data.size());
  if (len > bufsize) return -2;
  std::memcpy(buf, rec.data.data(), len);
  r->ring.pop_front();
  r->cv_can_push.notify_one();
  return len;
}

// Peek the next record's length without consuming it (-1 at end-of-epoch).
int64_t rio_reader_peek_len(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  std::unique_lock<std::mutex> lk(r->mu);
  r->cv_can_pop.wait(lk, [&] { return !r->ring.empty() || r->eof; });
  if (r->ring.empty()) return -1;
  return static_cast<int64_t>(r->ring.front().data.size());
}

// Pop up to n records into one contiguous buffer (batch assembly in native
// code: one ctypes crossing per batch instead of per record). sizes[i]
// receives each record's length. Returns the number of records copied
// (0 at end-of-epoch); records that would overflow bufsize stay queued.
int64_t rio_reader_next_batch(void* handle, int64_t n, char* buf,
                              int64_t bufsize, int64_t* sizes) {
  auto* r = static_cast<Reader*>(handle);
  int64_t count = 0;
  int64_t used = 0;
  std::unique_lock<std::mutex> lk(r->mu);
  while (count < n) {
    r->cv_can_pop.wait(lk, [&] { return !r->ring.empty() || r->eof; });
    if (r->ring.empty()) break;  // epoch exhausted
    Record& rec = r->ring.front();
    int64_t len = static_cast<int64_t>(rec.data.size());
    if (used + len > bufsize) {
      if (count == 0) return -2;  // first record alone exceeds the buffer
      break;
    }
    std::memcpy(buf + used, rec.data.data(), len);
    sizes[count] = len;
    used += len;
    ++count;
    r->ring.pop_front();
    r->cv_can_push.notify_one();
  }
  return count;
}

// Restart from the beginning (next epoch; reshuffles in shuffle mode).
void rio_reader_reset(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->stop = true;
    r->cv_can_push.notify_all();
  }
  if (r->worker.joinable()) r->worker.join();
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->ring.clear();
    r->stop = false;
    r->eof = false;
    r->epoch += 1;
  }
  r->worker = std::thread([r] { r->run(); });
}

void rio_reader_destroy(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->stop = true;
    r->cv_can_push.notify_all();
  }
  if (r->worker.joinable()) r->worker.join();
  delete r;
}

// --- writer ----------------------------------------------------------------

void* rio_writer_create(const char* path) {
  auto* w = new Writer();
  w->f = std::fopen(path, "wb");
  if (!w->f) { delete w; return nullptr; }
  return w;
}

// Returns the byte offset the record was written at, or -1 on error.
int64_t rio_writer_write(void* handle, const char* buf, int64_t len) {
  auto* w = static_cast<Writer*>(handle);
  // lengths at or above 2^29 would leak into the header's continue-flag
  // bits and corrupt the stream
  if (len < 0 || len >= (int64_t(1) << 29)) return -1;
  int64_t pos = std::ftell(w->f);
  uint32_t head[2] = {kMagic, static_cast<uint32_t>(len)};
  if (std::fwrite(head, sizeof(uint32_t), 2, w->f) != 2) return -1;
  if (std::fwrite(buf, 1, len, w->f) != static_cast<size_t>(len)) return -1;
  int64_t pad = (4 - len % 4) % 4;
  static const char zeros[4] = {0, 0, 0, 0};
  if (pad && std::fwrite(zeros, 1, pad, w->f) != static_cast<size_t>(pad))
    return -1;
  return pos;
}

void rio_writer_destroy(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  if (w->f) std::fclose(w->f);
  delete w;
}

}  // extern "C"
