// Native JPEG decode + augment pipeline (reference
// src/io/iter_image_recordio_2.cc:880 threaded decode + image_aug_default.cc
// resize/crop/flip/normalize, rebuilt for the TPU host runtime).
//
// One C call decodes a BATCH: an internal pthread pool decompresses each
// JPEG with libjpeg, bilinear-resizes the short side, random/center-crops
// to the target, optionally mirrors, and writes normalized float32 CHW
// directly into the caller's output buffer. The GIL is released for the
// whole batch, so Python-side prefetch overlaps fully.
//
// Exposed via ctypes (mxnet_tpu/native/__init__.py); falls back to the
// Python/PIL path when libjpeg is unavailable at build time.

#include <cstddef>
#include <cstdio>

#include <jpeglib.h>

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct ErrMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void err_exit(j_common_ptr cinfo) {
  ErrMgr* err = reinterpret_cast<ErrMgr*>(cinfo->err);
  longjmp(err->jump, 1);
}

// xorshift64* — deterministic per-image stream, seed mixed with the image
// index so results are independent of which worker picks the image up
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed ? seed : 0x9e3779b97f4a7c15ull) {}
  uint64_t next() {
    s ^= s >> 12;
    s ^= s << 25;
    s ^= s >> 27;
    return s * 0x2545f4914f6cdd1dull;
  }
  // uniform in [0, n)
  int64_t below(int64_t n) { return n > 0 ? (int64_t)(next() % (uint64_t)n) : 0; }
};

// decode one JPEG -> RGB8; returns false on corrupt input
bool decode_rgb(const unsigned char* buf, int64_t len,
                std::vector<unsigned char>* out, int* w, int* h) {
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = err_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf), (unsigned long)len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  out->resize((size_t)(*w) * (*h) * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char* row = out->data() + (size_t)cinfo.output_scanline * (*w) * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// bilinear resize RGB8 (sw, sh) -> (dw, dh)
void resize_rgb(const unsigned char* src, int sw, int sh,
                std::vector<unsigned char>* dst, int dw, int dh) {
  dst->resize((size_t)dw * dh * 3);
  const float sx = (float)sw / dw, sy = (float)sh / dh;
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    int y0 = (int)std::floor(fy);
    float wy = fy - y0;
    int y1 = y0 + 1;
    if (y0 < 0) y0 = 0;
    if (y1 >= sh) y1 = sh - 1;
    if (y0 >= sh) y0 = sh - 1;
    for (int x = 0; x < dw; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      int x0 = (int)std::floor(fx);
      float wx = fx - x0;
      int x1 = x0 + 1;
      if (x0 < 0) x0 = 0;
      if (x1 >= sw) x1 = sw - 1;
      if (x0 >= sw) x0 = sw - 1;
      for (int c = 0; c < 3; ++c) {
        float v00 = src[((size_t)y0 * sw + x0) * 3 + c];
        float v01 = src[((size_t)y0 * sw + x1) * 3 + c];
        float v10 = src[((size_t)y1 * sw + x0) * 3 + c];
        float v11 = src[((size_t)y1 * sw + x1) * 3 + c];
        float v = (1 - wy) * ((1 - wx) * v00 + wx * v01) +
                  wy * ((1 - wx) * v10 + wx * v11);
        (*dst)[((size_t)y * dw + x) * 3 + c] = (unsigned char)(v + 0.5f);
      }
    }
  }
}

struct Pipeline {
  int out_h, out_w;
  int resize_short;     // 0 = only resize when smaller than crop
  int rand_crop, rand_mirror;
  uint64_t seed;
  float mean[3], std[3];
  int nthreads;
};

// decode+augment ONE image into out (3*out_h*out_w float32 CHW)
bool process_one(const Pipeline& p, const unsigned char* buf, int64_t len,
                 uint64_t img_idx, float* out) {
  std::vector<unsigned char> rgb;
  int w = 0, h = 0;
  if (!decode_rgb(buf, len, &rgb, &w, &h)) return false;

  Rng rng(p.seed * 0x9e3779b97f4a7c15ull + img_idx + 1);

  // final dims BEFORE cropping: resize-short if requested, then clamp
  // each dim independently so the crop always fits — the clamp must
  // apply even when the short side already equals the target or no
  // resize was requested (otherwise the crop reads out of bounds)
  int short_side = w < h ? w : h;
  int dw = w, dh = h;
  if (p.resize_short > 0 && short_side != p.resize_short) {
    float scale = (float)p.resize_short / short_side;
    dw = (int)std::lround(w * scale);
    dh = (int)std::lround(h * scale);
  }
  if (dw < p.out_w) dw = p.out_w;
  if (dh < p.out_h) dh = p.out_h;
  std::vector<unsigned char> resized;
  const unsigned char* img = rgb.data();
  int iw = w, ih = h;
  if (dw != w || dh != h) {
    resize_rgb(rgb.data(), w, h, &resized, dw, dh);
    img = resized.data();
    iw = dw;
    ih = dh;
  }
  int x0, y0;
  if (p.rand_crop) {
    x0 = (int)rng.below(iw - p.out_w + 1);
    y0 = (int)rng.below(ih - p.out_h + 1);
  } else {
    x0 = (iw - p.out_w) / 2;
    y0 = (ih - p.out_h) / 2;
  }
  bool mirror = p.rand_mirror && (rng.next() & 1);
  const size_t plane = (size_t)p.out_h * p.out_w;
  for (int y = 0; y < p.out_h; ++y) {
    const unsigned char* row = img + ((size_t)(y0 + y) * iw + x0) * 3;
    for (int x = 0; x < p.out_w; ++x) {
      int sx = mirror ? (p.out_w - 1 - x) : x;
      const unsigned char* px = row + (size_t)sx * 3;
      for (int c = 0; c < 3; ++c) {
        out[c * plane + (size_t)y * p.out_w + x] =
            ((float)px[c] - p.mean[c]) / p.std[c];
      }
    }
  }
  return true;
}

struct Decoder {
  Pipeline pipe;
  uint64_t epoch_offset = 0;  // advances per batch so streams don't repeat
};

}  // namespace

extern "C" {

void* jdec_create(int out_h, int out_w, int resize_short, int rand_crop,
                  int rand_mirror, uint64_t seed, int nthreads,
                  const float* mean3, const float* std3) {
  Decoder* d = new Decoder();
  d->pipe.out_h = out_h;
  d->pipe.out_w = out_w;
  d->pipe.resize_short = resize_short;
  d->pipe.rand_crop = rand_crop;
  d->pipe.rand_mirror = rand_mirror;
  d->pipe.seed = seed;
  d->pipe.nthreads = nthreads > 0 ? nthreads : 1;
  for (int c = 0; c < 3; ++c) {
    d->pipe.mean[c] = mean3 ? mean3[c] : 0.0f;
    d->pipe.std[c] = (std3 && std3[c] != 0.0f) ? std3[c] : 1.0f;
  }
  return d;
}

// bufs: n concatenated jpeg payloads; lens[i] their sizes.
// out: n * 3 * out_h * out_w float32. ok[i]=1 decoded, 0 corrupt.
// Returns number decoded, -1 on bad handle.
int64_t jdec_decode_batch(void* handle, int64_t n, const char* bufs,
                          const int64_t* lens, float* out, int8_t* ok) {
  Decoder* d = static_cast<Decoder*>(handle);
  if (!d) return -1;
  std::vector<int64_t> offs(n);
  int64_t acc = 0;
  for (int64_t i = 0; i < n; ++i) {
    offs[i] = acc;
    acc += lens[i];
  }
  const size_t img_f = (size_t)3 * d->pipe.out_h * d->pipe.out_w;
  std::atomic<int64_t> next(0), done_ok(0);
  const uint64_t base = d->epoch_offset;
  auto worker = [&]() {
    for (;;) {
      int64_t i = next.fetch_add(1);
      if (i >= n) return;
      bool good = process_one(
          d->pipe, reinterpret_cast<const unsigned char*>(bufs + offs[i]),
          lens[i], base + (uint64_t)i, out + (size_t)i * img_f);
      ok[i] = good ? 1 : 0;
      if (good) done_ok.fetch_add(1);
      if (!good) memset(out + (size_t)i * img_f, 0, img_f * sizeof(float));
    }
  };
  int nt = d->pipe.nthreads;
  if (nt > n) nt = (int)n;
  if (nt <= 1) {
    worker();
  } else {
    std::vector<std::thread> threads;
    threads.reserve(nt);
    for (int t = 0; t < nt; ++t) threads.emplace_back(worker);
    for (auto& t : threads) t.join();
  }
  d->epoch_offset += (uint64_t)n;
  return done_ok.load();
}

void jdec_reset(void* handle) {
  Decoder* d = static_cast<Decoder*>(handle);
  if (d) d->epoch_offset = 0;
}

void jdec_destroy(void* handle) { delete static_cast<Decoder*>(handle); }

}  // extern "C"
