// Example compiled operator library for the mxtpu external-op ABI —
// the TPU-native analog of the reference's lib_api.h custom-op .so
// (include/mxnet/lib_api.h:1-1023, loaded by MXLoadLib).
//
// ABI v1 (all float32, single output; see mxnet_tpu/library.py):
//   int         mxtpu_oplib_abi_version(void)           -> 1
//   int         mxtpu_oplib_count(void)
//   const char* mxtpu_oplib_name(int idx)
//   int mxtpu_oplib_infer(idx, n_in, shapes, ndims, out_shape, out_ndim)
//   int mxtpu_oplib_forward(idx, n_in, inputs, shapes, ndims,
//                           out, out_shape, out_ndim)
//
// Build: g++ -O2 -std=c++17 -shared -fPIC oplib_example.cc -o libmyops.so

#include <cmath>
#include <cstdint>
#include <cstring>

namespace {

int64_t numel(const int64_t* shape, int ndim) {
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  return n;
}

}  // namespace

extern "C" {

int mxtpu_oplib_abi_version(void) { return 1; }

int mxtpu_oplib_count(void) { return 2; }

const char* mxtpu_oplib_name(int idx) {
  switch (idx) {
    case 0: return "scaled_sqrt";   // y = 2 * sqrt(|x|)
    case 1: return "pairwise_add";  // y = a + b (same shape)
    default: return nullptr;
  }
}

int mxtpu_oplib_infer(int idx, int n_in, const int64_t* const* shapes,
                      const int* ndims, int64_t* out_shape, int* out_ndim) {
  if (idx == 0 && n_in == 1) {
    *out_ndim = ndims[0];
    std::memcpy(out_shape, shapes[0], sizeof(int64_t) * ndims[0]);
    return 0;
  }
  if (idx == 1 && n_in == 2) {
    if (ndims[0] != ndims[1]) return -1;
    for (int i = 0; i < ndims[0]; ++i)
      if (shapes[0][i] != shapes[1][i]) return -1;
    *out_ndim = ndims[0];
    std::memcpy(out_shape, shapes[0], sizeof(int64_t) * ndims[0]);
    return 0;
  }
  return -1;
}

int mxtpu_oplib_forward(int idx, int n_in, const float* const* inputs,
                        const int64_t* const* shapes, const int* ndims,
                        float* out, const int64_t* out_shape, int out_ndim) {
  (void)shapes;
  const int64_t n = numel(out_shape, out_ndim);
  if (idx == 0 && n_in == 1) {
    for (int64_t i = 0; i < n; ++i)
      out[i] = 2.0f * std::sqrt(std::fabs(inputs[0][i]));
    return 0;
  }
  if (idx == 1 && n_in == 2) {
    for (int64_t i = 0; i < n; ++i) out[i] = inputs[0][i] + inputs[1][i];
    return 0;
  }
  return -1;
}

}  // extern "C"
